"""Unified telemetry: metrics registry + export renderers, mergeable
cross-rank timelines, the elastic event log, and the driver's HTTP
``/metrics`` + ``/health`` endpoint."""

import json
import os
import queue
import threading
import urllib.request

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import events, metrics
from horovod_tpu.utils.timeline import Timeline, merge_timeline_files


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset_counters()
    events.set_event_log(None)
    yield
    metrics.reset_counters()
    events.set_event_log(None)


# ---------------------------------------------------------------- registry
class TestMetricsRegistry:
    def test_counters_back_compat(self):
        metrics.inc_counter("retry.x.attempts")
        metrics.inc_counter("retry.x.attempts", 2)
        assert metrics.get_counter("retry.x.attempts") == 3
        assert metrics.get_counters("retry.") == {"retry.x.attempts": 3}
        metrics.reset_counters("retry.")
        assert metrics.get_counter("retry.x.attempts") == 0

    def test_gauges_with_labels(self):
        metrics.set_gauge("stall.current_stalled", 2)
        metrics.set_gauge("stall.stalled", 1, labels={"op": "allreduce.g"})
        metrics.set_gauge("stall.stalled", 1, labels={"op": "allgather.e"})
        assert metrics.get_gauge("stall.current_stalled") == 2
        assert metrics.get_gauge(
            "stall.stalled", labels={"op": "allreduce.g"}
        ) == 1
        metrics.clear_gauge("stall.stalled")
        assert metrics.get_gauge(
            "stall.stalled", labels={"op": "allreduce.g"}
        ) is None
        # the other family survives a targeted clear
        assert metrics.get_gauge("stall.current_stalled") == 2

    def test_histogram_buckets(self):
        metrics.observe("lat", 0.003)
        metrics.observe("lat", 0.02)
        metrics.observe("lat", 999.0)  # lands in +Inf
        h = metrics.get_histogram("lat")
        assert h["count"] == 3
        assert h["sum"] == pytest.approx(999.023)
        assert sum(h["counts"]) == 3
        assert h["counts"][-1] == 1  # the +Inf slot

    def test_prometheus_render(self):
        metrics.inc_counter("elastic.rounds", 4)
        metrics.set_gauge("elastic.workers", 2)
        metrics.set_gauge("stall.stalled", 1, labels={"op": "a.b"})
        metrics.observe("checkpoint.write_seconds", 0.004)
        text = metrics.render_prometheus()
        assert "# TYPE hvd_tpu_elastic_rounds_total counter" in text
        assert "hvd_tpu_elastic_rounds_total 4" in text
        assert "hvd_tpu_elastic_workers 2" in text
        assert 'hvd_tpu_stall_stalled{op="a.b"} 1.0' in text
        assert "# TYPE hvd_tpu_checkpoint_write_seconds histogram" in text
        assert 'hvd_tpu_checkpoint_write_seconds_bucket{le="0.005"} 1' in text
        assert 'hvd_tpu_checkpoint_write_seconds_bucket{le="+Inf"} 1' in text
        assert "hvd_tpu_checkpoint_write_seconds_count 1" in text

    def test_prometheus_bucket_counts_are_cumulative(self):
        metrics.observe("lat", 0.003)
        metrics.observe("lat", 0.02)
        text = metrics.render_prometheus()
        assert 'hvd_tpu_lat_bucket{le="0.005"} 1' in text
        assert 'hvd_tpu_lat_bucket{le="0.025"} 2' in text
        assert 'hvd_tpu_lat_bucket{le="60.0"} 2' in text

    def test_snapshot_roundtrips_through_json_with_rank_label(self):
        metrics.inc_counter("train.steps", 7)
        metrics.observe("lat", 0.1)
        snap = json.loads(metrics.render_json())
        text = metrics.render_prometheus(snap, extra_labels={"rank": "3"})
        assert 'hvd_tpu_train_steps_total{rank="3"} 7' in text
        assert 'hvd_tpu_lat_bucket{le="+Inf",rank="3"} 1' in text

    def test_reset_clears_gauges_and_histograms(self):
        metrics.set_gauge("g", 1)
        metrics.observe("h", 1.0)
        metrics.reset_counters()
        assert metrics.get_gauge("g") is None
        assert metrics.get_histogram("h") is None

    def test_quantile_interpolates_within_buckets(self):
        metrics.reset_counters("q.")
        # 4 observations in known buckets: (0.0025, 0.005] x2,
        # (0.005, 0.01] x1, (0.01, 0.025] x1
        for v in (0.003, 0.004, 0.007, 0.02):
            metrics.observe("q.lat", v)
        # p50: target rank 2 lands at the top of the first bucket ->
        # linear interpolation gives exactly its upper bound
        assert metrics.quantile("q.lat", 0.5) == pytest.approx(0.005)
        # p100 clamps to the last occupied bucket's bound
        assert metrics.quantile("q.lat", 1.0) == pytest.approx(0.025)
        # p0 pins to the first occupied bucket's lower edge
        assert metrics.quantile("q.lat", 0.0) == pytest.approx(0.0025)
        assert metrics.quantile("missing", 0.5) is None
        with pytest.raises(ValueError):
            metrics.quantile("q.lat", 1.5)

    def test_quantile_overflow_bucket_clamps(self):
        metrics.reset_counters("q.")
        metrics.observe("q.inf", 999.0)  # +Inf slot only
        assert metrics.quantile("q.inf", 0.5) == pytest.approx(60.0)

    def test_prometheus_renders_quantile_lines(self):
        metrics.reset_counters("q.")
        for v in (0.003, 0.004, 0.007, 0.02):
            metrics.observe("q.lat", v)
        text = metrics.render_prometheus()
        assert 'hvd_tpu_q_lat{quantile="0.5"} 0.005' in text
        assert 'hvd_tpu_q_lat{quantile="0.99"}' in text
        # quantile lines respect extra labels like every other series
        snap = json.loads(metrics.render_json())
        text = metrics.render_prometheus(snap, extra_labels={"rank": "2"})
        assert 'hvd_tpu_q_lat{quantile="0.5",rank="2"} 0.005' in text


# ------------------------------------------------------- eager hot path
class TestCollectiveInstrumentation:
    def test_allreduce_feeds_registry(self):
        hvd.init()
        try:
            x = np.ones((8, 4), np.float32)
            for _ in range(2):
                try:
                    hvd.allreduce(x, name="probe")
                    dispatched = True
                except Exception:
                    # dispatch backends can be broken in CI (e.g. jax
                    # API drift); _record still runs pre-dispatch, so
                    # the byte/dispatch accounting is assertable either
                    # way — only the latency histogram needs a
                    # completed dispatch.
                    dispatched = False
            assert metrics.get_counter("collective.allreduce.dispatches") == 2
            assert metrics.get_counter("collective.allreduce.bytes") == \
                2 * x.size * 4
            hb = metrics.get_histogram("collective.allreduce.bytes_hist")
            assert hb is not None and hb["count"] == 2
            if dispatched:
                h = metrics.get_histogram(
                    "collective.allreduce.dispatch_seconds"
                )
                assert h is not None and h["count"] == 2
        finally:
            hvd.shutdown()

    def test_timed_dispatch_observes_latency(self):
        from horovod_tpu.ops.eager import _timed

        out = _timed("ALLREDUCE", lambda v: v + 1, 41)
        assert out == 42
        h = metrics.get_histogram("collective.allreduce.dispatch_seconds")
        assert h is not None and h["count"] == 1


# ------------------------------------------------------------- timelines
def _write_synthetic_trace(path, rank, epoch_us, ts_list):
    evts = [
        {"name": "process_name", "ph": "M", "pid": 4000 + rank,
         "args": {"name": f"orig {rank}"}},
        {"name": "process_sort_index", "ph": "M", "pid": 4000 + rank,
         "args": {"sort_index": 99}},
        {"name": "HVD_PROC_META", "ph": "i", "ts": 0.0, "s": "p",
         "pid": 4000 + rank, "tid": 0,
         "args": {"rank": rank, "hostname": f"host{rank}",
                  "pid": 4000 + rank, "epoch_wall_us": epoch_us}},
    ] + [
        {"name": "allreduce.grad", "cat": "ALLREDUCE", "ph": "X",
         "ts": ts, "dur": 5, "pid": 4000 + rank, "tid": 0,
         "args": {"bytes": 1024}}
        for ts in ts_list
    ]
    with open(path, "w") as fh:
        json.dump(evts, fh)


class TestTimelineMerge:
    def test_skewed_epochs_align_and_lanes_order(self, tmp_path):
        """Two per-rank traces with skewed wall-clock epochs merge into
        one Chrome trace: timestamps re-based onto the earliest epoch,
        pid lanes rewritten to ranks, rank order preserved regardless
        of argument order."""
        r0, r1 = tmp_path / "t.rank0.json", tmp_path / "t.rank1.json"
        _write_synthetic_trace(r0, 0, epoch_us=1_000_000.0,
                               ts_list=[100.0, 200.0])
        _write_synthetic_trace(r1, 1, epoch_us=1_500_000.0,
                               ts_list=[100.0])
        merged = merge_timeline_files([str(r1), str(r0)])  # reversed order
        evts = merged["traceEvents"]
        # valid Chrome trace JSON (round-trips)
        json.loads(json.dumps(merged))
        # lanes: pid == rank, rank 0 events come first
        pid_seq = [e["pid"] for e in evts]
        assert set(pid_seq) == {0, 1}
        assert pid_seq == sorted(pid_seq)
        # sort_index rewritten to the rank lane
        sort_idx = {e["pid"]: e["args"]["sort_index"] for e in evts
                    if e.get("name") == "process_sort_index"}
        assert sort_idx == {0: 0, 1: 1}
        # epoch skew folded in: rank1's ts=100 lands at 500_100us
        ops0 = [e["ts"] for e in evts
                if e["pid"] == 0 and e.get("cat") == "ALLREDUCE"]
        ops1 = [e["ts"] for e in evts
                if e["pid"] == 1 and e.get("cat") == "ALLREDUCE"]
        assert ops0 == [100.0, 200.0]
        assert ops1 == [500_100.0]

    def test_merge_without_metadata_falls_back(self, tmp_path):
        p = tmp_path / "legacy.json"
        with open(p, "w") as fh:
            json.dump([{"name": "x", "ph": "X", "ts": 1.0, "dur": 1,
                        "pid": 7, "tid": 0}], fh)
        merged = merge_timeline_files([str(p)])
        assert merged["traceEvents"][0]["pid"] == 0  # positional lane

    def test_real_timelines_carry_proc_meta(self, tmp_path):
        paths = []
        for rank in (0, 1):
            p = tmp_path / f"real.rank{rank}.json"
            tl = Timeline(str(p), rank=rank)
            tl.record_op("allreduce.w", "ALLREDUCE", 2048)
            tl.close()
            paths.append(str(p))
        for rank, p in enumerate(paths):
            evts = json.loads(open(p).read())
            meta = [e for e in evts if e.get("name") == "HVD_PROC_META"]
            assert len(meta) == 1
            assert meta[0]["args"]["rank"] == rank
            assert meta[0]["args"]["epoch_wall_us"] > 0
            names = [e.get("name") for e in evts]
            assert "process_name" in names and "thread_name" in names
        merged = merge_timeline_files(paths)
        cats = {e.get("cat") for e in merged["traceEvents"]}
        assert "ALLREDUCE" in cats

    def test_merge_cli(self, tmp_path):
        import subprocess
        import sys

        r0, r1 = tmp_path / "a.json", tmp_path / "b.json"
        _write_synthetic_trace(r0, 0, 0.0, [1.0])
        _write_synthetic_trace(r1, 1, 10.0, [1.0])
        out = tmp_path / "merged.json"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "tools/merge_timeline.py", str(r0), str(r1),
             "-o", str(out)],
            capture_output=True, text=True, cwd=repo, env=env,
        )
        assert proc.returncode == 0, proc.stderr
        merged = json.loads(out.read_text())
        assert {e["pid"] for e in merged["traceEvents"]} == {0, 1}

    def test_close_under_load_is_parseable(self, tmp_path):
        """Writers hammering record_op while close() runs must still
        leave a syntactically complete JSON array."""
        p = tmp_path / "load.json"
        tl = Timeline(str(p), rank=0)
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                tl.record_op(f"op{i % 16}", "ALLREDUCE", 64)
                i += 1

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        tl.close()
        stop.set()
        for t in threads:
            t.join()
        evts = json.loads(p.read_text())  # parseable or the test fails
        assert isinstance(evts, list)

    def test_put_counts_drops_and_logs_once(self):
        """Satellite: a full queue must not silently truncate the
        trace — the drop is counted and warned about exactly once."""
        tl = Timeline.__new__(Timeline)  # no writer thread needed
        tl.path = "<test>"
        tl._queue = queue.Queue(maxsize=1)
        tl._queue.put_nowait({"sentinel": True})
        tl._closed = threading.Event()
        tl._drop_logged = False
        before = metrics.get_counter("timeline.dropped_events")
        tl._put({"name": "x"})
        tl._put({"name": "y"})
        assert metrics.get_counter("timeline.dropped_events") == before + 2
        assert tl._drop_logged


# ------------------------------------------------------------ event log
class TestElasticEventLog:
    def test_emit_and_read_order(self, tmp_path):
        p = tmp_path / "elastic.jsonl"
        events.set_event_log(events.EventLog(str(p)))
        events.emit(events.ROUND_START, round=1, np=2)
        events.emit(events.WORKER_CRASH, round=1, worker_rank=1,
                    host="h1", verdict="crash")
        events.emit(events.BLACKLIST, host="h1", failures=1)
        events.emit(events.RESTART, round=1)
        events.set_event_log(None)
        evs = events.read_events(str(p))
        assert [e["event"] for e in evs] == [
            "round_start", "worker_crash", "blacklist", "restart",
        ]
        # both clocks present and monotonic-ordered within the process
        monos = [e["mono_ts"] for e in evs]
        assert monos == sorted(monos)
        assert all(e["wall_ts"] > 0 and "hostname" in e and "pid" in e
                   for e in evs)
        seqs = [e["seq"] for e in evs]
        assert seqs == [1, 2, 3, 4]

    def test_env_driven_log(self, tmp_path, monkeypatch):
        p = tmp_path / "env.jsonl"
        monkeypatch.setenv("HVD_TPU_ELASTIC_EVENT_LOG", str(p))
        events.reset()
        try:
            events.emit(events.DISCOVERY_CHANGE, hosts={"a": 2})
            assert events.read_events(str(p))[0]["event"] == \
                "discovery_change"
        finally:
            events.reset()
            monkeypatch.delenv("HVD_TPU_ELASTIC_EVENT_LOG")

    def test_no_log_is_noop(self):
        events.set_event_log(None)
        events.emit(events.ROUND_START, round=1)  # must not raise

    def test_blacklist_emits_event(self, tmp_path):
        from horovod_tpu.elastic.discovery import FixedHosts, HostManager

        p = tmp_path / "bl.jsonl"
        events.set_event_log(events.EventLog(str(p)))
        mgr = HostManager(FixedHosts({"h1": 2}), cooldown_s=0.01,
                          clock=lambda: 0.0)
        mgr.update_available_hosts()
        mgr.blacklist("h1")
        events.set_event_log(None)
        evs = events.read_events(str(p))
        assert evs and evs[0]["event"] == "blacklist"
        assert evs[0]["host"] == "h1" and evs[0]["failures"] == 1

    def test_torn_tail_is_skipped(self, tmp_path):
        p = tmp_path / "torn.jsonl"
        with open(p, "w") as fh:
            fh.write(json.dumps({"event": "round_start"}) + "\n")
            fh.write('{"event": "worker_cra')  # crashed mid-write
        evs = events.read_events(str(p))
        assert [e["event"] for e in evs] == ["round_start"]


# ------------------------------------------------------------- HTTP
class TestTelemetryHTTP:
    def test_metrics_and_health(self):
        from horovod_tpu.runner.telemetry_http import TelemetryServer

        metrics.inc_counter("elastic.rounds", 2)
        worker_snap = {"counters": {"train.steps": 5}, "gauges": [],
                       "histograms": {}}
        srv = TelemetryServer(
            port=0,
            health_fn=lambda: {"status": "ok", "round": 2, "workers": 1},
            workers_fn=lambda: [(0, worker_snap)],
        )
        try:
            base = f"http://127.0.0.1:{srv.port}"
            body = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert "hvd_tpu_elastic_rounds_total 2" in body
            assert 'hvd_tpu_train_steps_total{rank="0"} 5' in body
            health = json.loads(
                urllib.request.urlopen(f"{base}/health").read()
            )
            assert health["status"] == "ok" and health["round"] == 2
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/nope")
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_degraded_health_returns_503(self):
        from horovod_tpu.runner.telemetry_http import TelemetryServer

        srv = TelemetryServer(
            port=0, health_fn=lambda: {"status": "degraded"}
        )
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/health"
                )
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["status"] == "degraded"
        finally:
            srv.stop()

    def test_driver_wires_worker_pushes(self):
        """ElasticDriver._start_telemetry folds KV-pushed worker
        snapshots into the scrape and reports membership health."""
        from horovod_tpu.elastic.discovery import FixedHosts, HostManager
        from horovod_tpu.runner import hosts as hosts_mod
        from horovod_tpu.runner.elastic_driver import ElasticDriver

        pushed = {
            "rank_0": json.dumps(
                {"counters": {"train.steps": 9}, "gauges": [],
                 "histograms": {}}
            ).encode()
        }

        class FakeControl:
            def get(self, scope, key, timeout_ms=0):
                assert scope == "__metrics__"
                return pushed.get(key)

        mgr = HostManager(FixedHosts({"localhost": 2}))
        mgr.update_available_hosts()
        driver = ElasticDriver(mgr, min_np=1, telemetry_port=0)
        driver._last_assignments = hosts_mod.get_host_assignments(
            [hosts_mod.HostInfo("localhost", 1)], 1
        )
        srv = driver._start_telemetry(FakeControl())
        try:
            base = f"http://127.0.0.1:{srv.port}"
            body = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert 'hvd_tpu_train_steps_total{rank="0"} 9' in body
            health = json.loads(
                urllib.request.urlopen(f"{base}/health").read()
            )
            assert health["status"] == "ok"
            assert health["available_slots"] == 2
        finally:
            srv.stop()

    def test_driver_telemetry_port_from_env(self, monkeypatch):
        from horovod_tpu.elastic.discovery import FixedHosts, HostManager
        from horovod_tpu.runner.elastic_driver import ElasticDriver

        monkeypatch.setenv("HVD_TPU_TELEMETRY_PORT", "0")
        d = ElasticDriver(HostManager(FixedHosts({})), min_np=1)
        assert d.telemetry_port == 0
        monkeypatch.delenv("HVD_TPU_TELEMETRY_PORT")
        d2 = ElasticDriver(HostManager(FixedHosts({})), min_np=1)
        assert d2.telemetry_port is None


# ------------------------------------------------------------- stall gauge
class TestStallExport:
    def test_stall_surfaces_in_registry(self):
        from horovod_tpu.utils.stall import StallWatchdog

        wd = StallWatchdog(warn_seconds=0.05, poll_seconds=0.02)
        try:
            wd.begin("allreduce.stuck")
            import time

            deadline = time.monotonic() + 2.0
            while (metrics.get_counter("stall.warnings") == 0
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert metrics.get_counter("stall.warnings") >= 1
            assert metrics.get_gauge("stall.current_stalled") >= 1
            assert metrics.get_gauge(
                "stall.stalled", labels={"op": "allreduce.stuck"}
            ) == 1
            wd.end("allreduce.stuck")
            deadline = time.monotonic() + 2.0
            while (metrics.get_gauge("stall.current_stalled") != 0
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert metrics.get_gauge("stall.current_stalled") == 0
            assert metrics.get_gauge(
                "stall.stalled", labels={"op": "allreduce.stuck"}
            ) is None
        finally:
            wd.close()


# ------------------------------------------------------------- launcher
class TestLauncherFlags:
    def test_timeline_mark_cycles_flag(self):
        from horovod_tpu.runner.launch import env_from_args, parse_args

        args = parse_args(["-np", "2", "--timeline-mark-cycles",
                           "--", "python", "t.py"])
        env = env_from_args(args)
        assert env["HVD_TPU_TIMELINE_MARK_CYCLES"] == "1"
        args = parse_args(["-np", "2", "--", "python", "t.py"])
        assert "HVD_TPU_TIMELINE_MARK_CYCLES" not in env_from_args(args)

    def test_telemetry_port_flag_parses(self):
        from horovod_tpu.runner.launch import parse_args

        args = parse_args(["--min-np", "1", "-H", "localhost:2",
                           "--telemetry-port", "9090",
                           "--", "python", "t.py"])
        assert args.telemetry_port == 9090
