"""Callbacks (reference ``_keras/callbacks.py`` tests in test_keras.py):
LR schedule/warmup math, metric averaging, broadcast-at-start, and the
optax-native warmup schedule."""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.callbacks import (
    BroadcastGlobalVariablesCallback,
    Callback,
    CallbackList,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    TrainingLoop,
    warmup_schedule,
)


def test_schedule_staircase(hvd_module):
    cb = LearningRateScheduleCallback(
        multiplier=lambda epoch: 0.1 ** epoch, start_epoch=1, end_epoch=3
    )
    loop = TrainingLoop()
    for epoch, expected in [(0, 1.0), (1, 0.1), (2, 0.01), (3, 0.01)]:
        loop.epoch = epoch
        cb.on_epoch_begin(loop)
        assert loop.lr_multiplier == pytest.approx(expected)


def test_schedule_smooth_requires_steps_per_epoch(hvd_module):
    cb = LearningRateScheduleCallback(multiplier=lambda e: e, staircase=False)
    loop = TrainingLoop()
    with pytest.raises(ValueError):
        cb.on_batch_begin(loop)


def test_warmup_ramp(hvd_module):
    size = hvd.size()
    cb = LearningRateWarmupCallback(warmup_epochs=2, steps_per_epoch=10)
    loop = TrainingLoop()
    loop.epoch, loop.batch = 0, 0
    cb.on_batch_begin(loop)
    assert loop.lr_multiplier == pytest.approx(1.0 / size)
    loop.epoch, loop.batch = 1, 9
    cb.on_batch_begin(loop)
    mid = loop.lr_multiplier
    assert 1.0 / size < mid < 1.0
    loop.epoch, loop.batch = 2, 0
    cb.on_batch_begin(loop)
    assert loop.lr_multiplier == pytest.approx(1.0)


def test_broadcast_and_metric_callbacks(hvd_module):
    loop = TrainingLoop(params={"w": jnp.ones((2,))})
    cbs = CallbackList([
        BroadcastGlobalVariablesCallback(0), MetricAverageCallback(),
    ])
    cbs.on_train_begin(loop)
    np.testing.assert_allclose(np.asarray(loop.params["w"]), 1.0)
    loop.logs = {"loss": 0.25}
    cbs.on_epoch_end(loop)
    assert loop.logs["loss"] == pytest.approx(0.25)


def test_warmup_schedule_traced(hvd_module):
    size = hvd.size()
    sched = warmup_schedule(
        base_lr=0.1, warmup_epochs=2, steps_per_epoch=5
    )
    assert float(sched(0)) == pytest.approx(0.1)
    assert float(sched(10)) == pytest.approx(0.1 * size)
    assert float(sched(100)) == pytest.approx(0.1 * size)
    assert 0.1 < float(sched(5)) < 0.1 * size or size == 1


def test_callback_hook_order(hvd_module):
    calls = []

    class Recorder(Callback):
        def on_train_begin(self, loop):
            calls.append("train_begin")

        def on_epoch_begin(self, loop):
            calls.append("epoch_begin")

        def on_epoch_end(self, loop):
            calls.append("epoch_end")

        def on_train_end(self, loop):
            calls.append("train_end")

    loop = TrainingLoop()
    cbs = CallbackList([Recorder()])
    cbs.on_train_begin(loop)
    cbs.on_epoch_begin(loop)
    cbs.on_epoch_end(loop)
    cbs.on_train_end(loop)
    assert calls == ["train_begin", "epoch_begin", "epoch_end", "train_end"]
