"""Zero-downtime elastic remesh (``elastic/remesh.py``).

Three layers, matching the subsystem:

* **probe regressions** — the ``reinit_world`` evidence base
  (``tools/probe_remesh.py``) this is all built on;
* **layout exchange** — the old→new shard movement is a partition of
  the valid elements (every byte moves exactly once), checksums are
  preserved, the KV transport catches corruption, and a fault injected
  into any pipeline phase degrades to the checkpoint-restore path
  instead of wedging;
* **end to end** — an in-process 8→4 device resize whose post-remesh
  losses match the checkpoint-restart path BITWISE (f32 dense wire),
  the driver's remesh coordination against scripted workers (shrink,
  grow, ack-timeout fallback), and the real 4→3→4 process CPU resize
  (``multiproc`` — skipped where the CPU backend rejects cross-process
  computations).
"""

import hashlib
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

pytestmark = [pytest.mark.integration, pytest.mark.remesh]

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PYTHONPATH": REPO,
}


def test_probe_report_structure():
    """The committed findings artifact matches reality on this machine."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "probe_remesh.py")],
        capture_output=True, text=True, timeout=500,
        env={**os.environ, **_ENV},
    )
    assert proc.returncode == 0, proc.stderr[-400:]
    report = json.loads(proc.stdout)
    assert report["A_single_process_subset_remesh"]["works"]
    assert not report["B_multiprocess_world_resize"]["works"]
    assert report["B_multiprocess_world_resize"]["works_after_backend_reset"]


SURVIVOR = textwrap.dedent("""
    import os, sys
    import numpy as np
    import jax

    port = os.environ["PROBE_PORT"]
    rank = int(os.environ["PROBE_RANK"])
    os.environ["HVD_TPU_COORDINATOR_ADDR"] = f"127.0.0.1:{port}"
    os.environ["HVD_TPU_CROSS_SIZE"] = "2"
    os.environ["HVD_TPU_CROSS_RANK"] = str(rank)
    import horovod_tpu as hvd

    hvd.init()
    assert hvd.process_count() == 2
    # both ranks train happily...
    out = np.asarray(hvd.allreduce(
        np.ones((len(jax.local_devices()), 2), np.float32), op=hvd.Sum
    ))
    if rank == 1:
        sys.exit(0)  # ...then the peer dies

    # survivor re-meshes IN-PROCESS to a single-process world
    import horovod_tpu.elastic as elastic

    elastic.reinit_world()
    assert hvd.process_count() == 1
    y = np.asarray(hvd.allreduce(
        np.ones((hvd.size(), 3), np.float32), op=hvd.Sum
    ))
    assert y[0, 0] == float(hvd.size())
    print("SURVIVOR_REMESH_OK size=", hvd.size())
""")


@pytest.mark.multiproc
def test_survivor_reinit_world_in_process():
    from horovod_tpu.runner.launch import free_port

    port = free_port()
    env = {**os.environ, **_ENV, "PROBE_PORT": str(port)}
    p1 = subprocess.Popen(
        [sys.executable, "-c", SURVIVOR],
        env={**env, "PROBE_RANK": "1"},
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    p0 = subprocess.run(
        [sys.executable, "-c", SURVIVOR],
        env={**env, "PROBE_RANK": "0"},
        capture_output=True, text=True, timeout=300,
    )
    p1.wait(timeout=60)
    out = p0.stdout + p0.stderr
    assert p0.returncode == 0, out[-800:]
    assert "SURVIVOR_REMESH_OK" in out


def test_reinit_world_validates_partial_triple():
    import horovod_tpu.elastic as elastic

    with pytest.raises(ValueError, match="num_processes"):
        elastic.reinit_world(coordinator_address="10.0.0.5:1234")


# =====================================================================
# Layout exchange: the shard movement is a checksum-preserving
# permutation of the valid elements
# =====================================================================


class FakeKV:
    """In-memory stand-in for the rendezvous KV client."""

    def __init__(self):
        self.d = {}

    def put(self, scope, key, val):
        self.d[(scope, key)] = bytes(val)

    def get(self, scope, key, timeout_ms=0):
        return self.d.get((scope, key))


def _exchange(old, new, shards_old):
    from horovod_tpu.elastic import remesh as rm

    return {
        r: rm.apply_moves(
            rm.plan_moves(old, new, r), new.shard_len,
            np.float32, lambda s: shards_old[s],
        )
        for r in range(new.shards)
    }


class TestLayoutExchange:
    @pytest.mark.parametrize("old_shards,new_shards,n", [
        (4, 3, 10), (3, 4, 10), (8, 4, 37), (4, 8, 37),
        (1, 4, 5), (4, 1, 5), (2, 7, 64), (7, 2, 64),
        (4, 3, 2),  # n < both shard counts: mostly padding
    ])
    def test_moves_partition_valid_elements(self, old_shards,
                                            new_shards, n):
        """Across all destination ranks the moves cover every valid
        element exactly once — the exchange is a permutation."""
        from horovod_tpu.elastic import remesh as rm

        old = rm.ShardLayout(n=n, shards=old_shards,
                             shard_len=-(-n // old_shards))
        new = rm.ShardLayout(n=n, shards=new_shards,
                             shard_len=-(-n // new_shards))
        seen = np.zeros(n, np.int32)
        for r in range(new.shards):
            for m in rm.plan_moves(old, new, r):
                g0 = m.src_rank * old.shard_len + m.src_off
                seen[g0:g0 + m.length] += 1
                # destination offset names the same global interval
                assert g0 == r * new.shard_len + m.dst_off
        assert (seen == 1).all(), seen

    def test_roundtrip_preserves_checksum(self):
        """8 -> 3 -> 8: the full buffer (and its sha256) round-trips
        exactly, and padding never leaks into valid data."""
        from horovod_tpu.elastic import remesh as rm

        rng = np.random.RandomState(7)
        n = 101
        l8 = rm.ShardLayout(n=n, shards=8, shard_len=-(-n // 8))
        l3 = rm.ShardLayout(n=n, shards=3, shard_len=-(-n // 3))
        data = rng.randn(n).astype(np.float32)
        padded = np.zeros(l8.padded, np.float32)
        padded[:n] = data
        shards8 = {
            r: padded[r * l8.shard_len:(r + 1) * l8.shard_len]
            for r in range(8)
        }
        shards3 = _exchange(l8, l3, shards8)
        back8 = _exchange(l3, l8, shards3)
        digest = lambda a: hashlib.sha256(a.tobytes()).hexdigest()
        assert digest(rm.full_buffer(l3, shards3)) == digest(data)
        assert digest(rm.full_buffer(l8, back8)) == digest(data)
        # padding beyond n is zero-filled in every new shard
        lo, hi = l3.interval(2)
        assert (shards3[2][hi - 2 * l3.shard_len:] == 0).all() or \
            hi - 2 * l3.shard_len >= l3.shard_len

    def test_changed_length_raises(self):
        from horovod_tpu.elastic import remesh as rm
        from horovod_tpu.exceptions import RemeshError

        a = rm.ShardLayout(n=10, shards=2, shard_len=5)
        b = rm.ShardLayout(n=12, shards=2, shard_len=6)
        with pytest.raises(RemeshError, match="valid length"):
            rm.plan_moves(a, b, 0)

    def test_short_source_shard_raises(self):
        from horovod_tpu.elastic import remesh as rm
        from horovod_tpu.exceptions import RemeshError

        lay = rm.ShardLayout(n=8, shards=2, shard_len=4)
        moves = rm.plan_moves(lay, lay, 1)
        with pytest.raises(RemeshError, match="too short"):
            rm.apply_moves(moves, 4, np.float32,
                           lambda s: np.zeros(2, np.float32))


class TestPlanReshard:
    def _toy_layouts(self, world):
        import jax.numpy as jnp

        from horovod_tpu import sched
        from horovod_tpu.sched.zero1 import bucket_layouts

        params = {
            "a": jnp.zeros((13, 3), jnp.float32),
            "b": jnp.zeros((7,), jnp.float32),
            "c": jnp.zeros((4, 4), jnp.float32),
        }
        cfg = sched.SchedConfig(enabled=True, bucket_bytes=128,
                                lowering="flat")
        return bucket_layouts(params, world, cfg)

    def test_plan_pairs_buckets_across_worlds(self, hvd_init):
        from horovod_tpu.elastic import remesh as rm

        lays8 = self._toy_layouts(8)
        lays4 = self._toy_layouts(4)
        plan = rm.plan_reshard(lays8, lays4)
        assert len(plan.buckets) == len(lays8)
        for b in plan.buckets:
            assert b.old.n == b.new.n
        # every new rank's sources are computable and within the old world
        for r in range(4):
            assert all(0 <= s < 8 for s in plan.src_ranks(r))

    def test_membership_mismatch_raises(self, hvd_init):
        import dataclasses

        from horovod_tpu.elastic import remesh as rm
        from horovod_tpu.exceptions import RemeshError

        lays = self._toy_layouts(8)
        mutated = [dataclasses.replace(lays[0], indices=(99,))] + \
            list(lays[1:])
        with pytest.raises(RemeshError, match="membership"):
            rm.plan_reshard(lays, mutated)

    def test_reshard_bucket_state_moves_sharded_leaves(self, hvd_init):
        """Adam-like per-bucket states: (shard_len,) leaves move
        through the plan, scalar leaves are carried verbatim, EF dicts
        re-zero."""
        from horovod_tpu.elastic import remesh as rm

        lays8 = self._toy_layouts(8)
        lays4 = self._toy_layouts(4)
        plan = rm.plan_reshard(lays8, lays4)
        b = plan.buckets[0]
        rng = np.random.RandomState(3)
        full_m = rng.randn(b.old.padded).astype(np.float32)

        def old_state(rank):
            lo = rank * b.old.shard_len
            return {
                "m": full_m[lo:lo + b.old.shard_len],
                "count": np.asarray(5, np.int32),
            }

        outs = {
            r: rm.reshard_bucket_state(plan, 0, r, old_state)
            for r in range(b.new.shards)
        }
        got = rm.full_buffer(
            b.new, {r: outs[r]["m"] for r in outs}
        )
        np.testing.assert_array_equal(got, full_m[:b.old.n])
        assert all(int(outs[r]["count"]) == 5 for r in outs)
        # EF wrapper: residual re-zeros at the new padded length
        ef_out = rm.reshard_bucket_state(
            plan, 0, 0,
            lambda r: {"tx": old_state(r),
                       "ef": np.ones(b.old.padded, np.float32)},
        )
        assert ef_out["ef"].shape == (b.new.padded,)
        assert (ef_out["ef"] == 0).all()


class TestKVShardStore:
    def test_roundtrip(self):
        from horovod_tpu.elastic import remesh as rm

        store = rm.KVShardStore(FakeKV(), remesh_id=3)
        arr = np.arange(100000, dtype=np.float32)
        store.put(2, "zero.b0.l1", arr)
        got = store.get(2, "zero.b0.l1")
        np.testing.assert_array_equal(got, arr)
        assert got.dtype == arr.dtype

    def test_missing_shard_raises(self):
        from horovod_tpu.elastic import remesh as rm
        from horovod_tpu.exceptions import RemeshError

        store = rm.KVShardStore(FakeKV(), remesh_id=3)
        with pytest.raises(RemeshError, match="missing"):
            store.get(0, "nope")

    @pytest.mark.faults
    def test_corrupt_transport_is_caught(self):
        """An injected corruption of the published blob MUST surface
        as ShardChecksumError — never as silently wrong numerics."""
        from horovod_tpu import faults
        from horovod_tpu.elastic import remesh as rm
        from horovod_tpu.exceptions import ShardChecksumError

        store = rm.KVShardStore(FakeKV(), remesh_id=1)
        faults.set_plan("remesh.publish:corrupt:nth=1")
        try:
            store.put(0, "zero.b0.l0", np.ones(64, np.float32))
        finally:
            faults.set_plan(None)
        with pytest.raises(ShardChecksumError, match="sha256"):
            store.get(0, "zero.b0.l0")

    def test_roundtrip_through_real_controller(self):
        """The store speaks the actual rendezvous KV protocol."""
        from horovod_tpu.elastic import remesh as rm
        from horovod_tpu.runner import controller_py

        server = controller_py.make_server("s3cret", 1)
        client = controller_py.make_client(
            "127.0.0.1", server.port, "s3cret", rank=0
        )
        try:
            store = rm.KVShardStore(client, remesh_id=9)
            arr = np.arange(1 << 18, dtype=np.float32)
            store.put(1, "zero.b2.l0", arr)
            np.testing.assert_array_equal(
                store.get(1, "zero.b2.l0"), arr
            )
        finally:
            client.close()
            server.stop()


class TestRemeshRequest:
    def test_json_roundtrip(self):
        from horovod_tpu.elastic import remesh as rm

        req = rm.RemeshRequest(
            remesh_id=4, round_id=2, np_old=4, np_new=3,
            coordinator_addr="10.0.0.1:999",
            survivors={0: 0, 2: 1, 3: 2}, deadline_s=30.0,
        )
        back = rm.RemeshRequest.from_json(req.to_json())
        assert back == req
        assert back.new_rank(2) == 1
        assert back.new_rank(1) is None


# =====================================================================
# Worker pipeline: graceful degradation + shed path
# =====================================================================


class FakeManager:
    def __init__(self, rank=0, kv=None):
        self.rank = rank
        self._kv = kv or FakeKV()
        self.acks = []
        self.world_changes = []

    def kv_client(self):
        return self._kv

    def remesh_ack(self, remesh_id, phase):
        self.acks.append((phase, self.rank))
        self._kv.put("__remesh__", f"{phase}_{remesh_id}_{self.rank}",
                     b"1")

    def remesh_wait_go(self, remesh_id, timeout_s=60.0):
        return None  # driver already said go

    def on_world_changed(self, new_rank):
        self.world_changes.append(new_rank)
        self.rank = new_rank


class _PlainState:
    """Minimal state double: replicated attrs only."""

    def __init__(self):
        self.saved = self.restored = 0

    def save(self):
        self.saved += 1

    def restore(self):
        self.restored += 1

    def sharded_attrs(self):
        return {}


@pytest.mark.faults
class TestRunRemeshFallback:
    def _request(self, survivors, np_new=1):
        from horovod_tpu.elastic import remesh as rm

        return rm.RemeshRequest(
            remesh_id=11, round_id=1, np_old=1, np_new=np_new,
            coordinator_addr="127.0.0.1:1", survivors=survivors,
            deadline_s=2.0,
        )

    def test_phase_fault_degrades_to_remesh_error(self):
        """A fault in ANY pipeline phase surfaces as RemeshError (the
        elastic loop then exits for a checkpoint-restore round) and is
        counted as remesh.fallback."""
        from horovod_tpu import faults, metrics
        from horovod_tpu.elastic import remesh as rm
        from horovod_tpu.exceptions import RemeshError

        mgr = FakeManager(rank=0)
        state = _PlainState()
        before = metrics.get_counter("remesh.fallback")
        faults.set_plan("remesh.publish:error:nth=1")
        try:
            with pytest.raises(RemeshError):
                rm.run_remesh(state, mgr, self._request({0: 0}))
        finally:
            faults.set_plan(None)
        assert metrics.get_counter("remesh.fallback") == before + 1
        assert ("pause", 0) in mgr.acks

    def test_shed_rank_exits_with_shed_code(self):
        from horovod_tpu import metrics
        from horovod_tpu.elastic import remesh as rm

        mgr = FakeManager(rank=1)
        state = _PlainState()
        before = metrics.get_counter("remesh.shed")
        with pytest.raises(SystemExit) as exc:
            rm.run_remesh(state, mgr, self._request({0: 0}, np_new=1))
        assert exc.value.code == rm.REMESH_SHED_CODE
        assert metrics.get_counter("remesh.shed") == before + 1
        assert ("shed", 1) in mgr.acks
        # state was snapshotted + published before leaving
        assert state.saved == 1

    def test_abort_key_unblocks_barrier(self):
        """A worker stuck in the publish barrier sees the driver's
        abort and falls back instead of wedging."""
        from horovod_tpu.exceptions import RemeshError
        from horovod_tpu.runner.elastic_worker import (
            WorkerNotificationManager,
        )

        mgr = WorkerNotificationManager()
        kv = FakeKV()
        mgr._client = kv
        kv.put("__remesh__", "abort_7", b"1")
        with pytest.raises(RemeshError, match="abort"):
            mgr.remesh_wait_go(7, timeout_s=5.0)

    def test_barrier_timeout_raises(self):
        from horovod_tpu.exceptions import RemeshError
        from horovod_tpu.runner.elastic_worker import (
            WorkerNotificationManager,
        )

        mgr = WorkerNotificationManager()
        mgr._client = FakeKV()
        t0 = time.monotonic()
        with pytest.raises(RemeshError, match="no go/abort"):
            mgr.remesh_wait_go(8, timeout_s=1.0)
        assert time.monotonic() - t0 < 10


class TestOptimizerStateAcrossRemesh:
    def test_survivor_keeps_local_state_joiner_zeroes(self, hvd_init):
        """DistributedOptimizerState leaves are replicated or
        param-shaped rank-local: survivors carry them verbatim, a
        joiner cold-starts acc/residual at zero."""
        import jax.numpy as jnp

        from horovod_tpu.optim.distributed_optimizer import (
            DistributedOptimizerState,
            remesh_optimizer_state,
        )

        state = DistributedOptimizerState(
            counter=jnp.asarray(7, jnp.int32),
            acc={"w": jnp.ones((3,), jnp.float32)},
            inner=(jnp.zeros((2,)),),
            residual={"w": jnp.full((3,), 0.5, jnp.float32)},
        )
        kept = remesh_optimizer_state(state, joined=False)
        assert kept is state
        fresh = remesh_optimizer_state(state, joined=True)
        assert int(fresh.counter) == 7
        assert (np.asarray(fresh.acc["w"]) == 0).all()
        assert (np.asarray(fresh.residual["w"]) == 0).all()


# =====================================================================
# End to end: in-process device resize, losses match the restart path
# =====================================================================


def _quadratic_setup():
    import jax.numpy as jnp

    X = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    Y = (X @ np.full((4, 3), 0.3)).astype(np.float32)

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w1"] @ p["w2"] + p["b"] - y) ** 2)

    def fresh_params():
        return {
            "w1": jnp.full((4, 5), 0.2, jnp.float32),
            "w2": jnp.full((5, 3), 0.5, jnp.float32),
            "b": jnp.zeros((3,), jnp.float32),
        }

    return loss_fn, fresh_params, (jnp.asarray(X), jnp.asarray(Y))


def test_in_process_resize_matches_restart_path():
    """The acceptance invariant on the CPU-testable analog of a
    kill-and-resize: train bucketed ZeRO-1 on 8 devices, remesh the
    live state to a 4-device world through the full resharder (host
    snapshot -> KV publish -> plan -> fetch -> install), and the
    post-remesh losses are BITWISE equal to restoring the same
    boundary state through the checkpoint-restart path (f32 dense
    wire)."""
    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu import metrics, sched
    from horovod_tpu import runtime as rt
    from horovod_tpu.elastic import ArrayState, remesh as rm
    from horovod_tpu.sched.zero1 import bucket_layouts
    from horovod_tpu.topo import model as topo_model

    loss_fn, fresh_params, batch = _quadratic_setup()
    cfg = sched.SchedConfig(enabled=True, bucket_bytes=48,
                            lowering="flat")
    tx = optax.adam(0.05)
    steps = 3
    try:
        hvd.init()
        step = sched.bucketed_zero_step(loss_fn, tx, cfg=cfg)
        params = fresh_params()
        states = step.init(params)
        for _ in range(steps):
            params, states, _ = step(params, states, batch)

        # -- remesh boundary: run the resharder end to end ------------
        state = ArrayState(params=params, opt_state=states)
        spec = rm.ShardedZeroState(state, "params", "opt_state",
                                   cfg=cfg)
        req = rm.RemeshRequest(
            remesh_id=1, round_id=1, np_old=1, np_new=1,
            coordinator_addr="", survivors={0: 0},
            dev_old=8, dev_new=4,
        )
        success_before = metrics.get_counter("remesh.success")
        spec.snapshot()
        store = rm.KVShardStore(FakeKV(), 1)
        spec.publish(store, "zero", 0)
        host_states = spec.reshard(req, store, "zero", 0)
        host_params = jax.device_get(params)
        snap_states = jax.device_get(states)  # the "checkpoint"

        # -- new 4-device world: remesh path --------------------------
        rt.shutdown()
        topo_model.reset()
        hvd.init(devices=jax.devices()[:4])
        step4 = sched.bucketed_zero_step(loss_fn, tx, cfg=cfg)
        p4 = jax.device_put(host_params)
        step4.init(p4)  # rebuild layouts; fresh values discarded
        spec.install(host_states)
        losses_remesh = []
        st4 = state.opt_state
        for _ in range(steps):
            p4, st4, loss = step4(p4, st4, batch)
            losses_remesh.append(float(loss))

        # -- reference: checkpoint-restore onto the same world --------
        lays8 = bucket_layouts(fresh_params(), 8, cfg)
        lays4 = bucket_layouts(fresh_params(), 4, cfg)
        mesh = rt.get_runtime().mesh

        def restore_bucket(full_like, lay8, lay4):
            def leaf(x):
                arr = np.asarray(x)
                if arr.ndim >= 1 and arr.shape[0] == lay8.padded:
                    out = np.zeros((lay4.padded,), arr.dtype)
                    out[:lay8.n] = arr[:lay8.n]
                    return jax.device_put(
                        out, NamedSharding(mesh, P("hvd"))
                    )
                return jax.device_put(arr, NamedSharding(mesh, P()))

            return jax.tree.map(leaf, full_like)

        states_ref = tuple(
            restore_bucket(snap_states[bi], lays8[bi], lays4[bi])
            for bi in range(len(snap_states))
        )
        step4b = sched.bucketed_zero_step(loss_fn, tx, cfg=cfg)
        p4b = jax.device_put(host_params)
        step4b.init(p4b)
        losses_restore = []
        for _ in range(steps):
            p4b, states_ref, loss = step4b(p4b, states_ref, batch)
            losses_restore.append(float(loss))

        assert losses_remesh == losses_restore, (
            losses_remesh, losses_restore,
        )
    finally:
        rt.shutdown()
        topo_model.reset()


def test_in_process_grow_matches_restart_path():
    """The grow direction (4 -> 8 devices) through the same pipeline:
    newcomer shards assemble from the published old slabs."""
    import jax
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import sched
    from horovod_tpu import runtime as rt
    from horovod_tpu.elastic import ArrayState, remesh as rm
    from horovod_tpu.topo import model as topo_model

    loss_fn, fresh_params, batch = _quadratic_setup()
    cfg = sched.SchedConfig(enabled=True, bucket_bytes=48,
                            lowering="flat")
    tx = optax.adam(0.05)
    try:
        hvd.init(devices=None)
        rt.shutdown()
        topo_model.reset()
        hvd.init(devices=jax.devices()[:4])
        step = sched.bucketed_zero_step(loss_fn, tx, cfg=cfg)
        params = fresh_params()
        states = step.init(params)
        for _ in range(3):
            params, states, _ = step(params, states, batch)

        state = ArrayState(params=params, opt_state=states)
        spec = rm.ShardedZeroState(state, "params", "opt_state",
                                   cfg=cfg)
        req = rm.RemeshRequest(
            remesh_id=2, round_id=1, np_old=1, np_new=1,
            coordinator_addr="", survivors={0: 0},
            dev_old=4, dev_new=8,
        )
        spec.snapshot()
        store = rm.KVShardStore(FakeKV(), 2)
        spec.publish(store, "zero", 0)
        host_states = spec.reshard(req, store, "zero", 0)
        host_params = jax.device_get(params)

        rt.shutdown()
        topo_model.reset()
        hvd.init()  # all 8 devices
        step8 = sched.bucketed_zero_step(loss_fn, tx, cfg=cfg)
        p8 = jax.device_put(host_params)
        step8.init(p8)
        spec.install(host_states)
        st8 = state.opt_state
        l_first = None
        for _ in range(2):
            p8, st8, loss = step8(p8, st8, batch)
            l_first = float(loss) if l_first is None else l_first
        # losses keep descending from the 4-device trajectory (the
        # batch is identical, so the first post-grow loss must equal
        # the loss a never-resized run would see at this point — the
        # shrink test proves bitwise equality; here we assert sane
        # continuation)
        assert l_first < 0.3
    finally:
        rt.shutdown()
        topo_model.reset()


# =====================================================================
# Driver coordination: pause/ack/go/done barriers against scripted
# workers speaking the real KV protocol (no jax worlds involved, so
# this runs even where the CPU backend rejects cross-process
# computations)
# =====================================================================


class ScriptedRemeshWorker:
    """A worker_factory product that speaks the remesh KV protocol the
    way ``elastic/run.py`` + ``elastic_worker.py`` do — without a jax
    world, so the driver's coordination is testable anywhere."""

    def __init__(self, rank, hostname, command, env, ssh_port=None,
                 ssh_identity_file=None, obey_remesh=True):
        from horovod_tpu.runner import controller_py

        self.rank = rank
        self.env = env
        self.obey_remesh = obey_remesh
        self._rc = None
        self._stop = threading.Event()
        self._client = controller_py.make_client(
            env["HVD_TPU_RENDEZVOUS_ADDR"],
            int(env["HVD_TPU_RENDEZVOUS_PORT"]),
            env["HVD_TPU_SECRET"], rank,
        )
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    @property
    def returncode(self):
        return self._rc

    def terminate(self):
        self._stop.set()

    def wait(self):
        self._thread.join(timeout=30)
        if self._rc is None:
            self._rc = -15
        return self._rc

    def _get(self, scope, key):
        try:
            return self._client.get(scope, key, timeout_ms=0)
        except Exception:
            return None

    def _run(self):
        from horovod_tpu.elastic.remesh import (
            REMESH_SHED_CODE,
            RemeshRequest,
        )

        round_id = self.env["HVD_TPU_ELASTIC_ROUND"]
        rank = self.rank
        join_id = self.env.get("HVD_TPU_REMESH_JOIN")
        handled = set()
        try:
            while not self._stop.is_set():
                if self._get("__test__", f"finish_round_{round_id}"):
                    self._rc = 0
                    return
                raw = self._get("__remesh__", f"begin_{round_id}")
                req = None
                if raw is not None and self.obey_remesh:
                    req = RemeshRequest.from_json(raw.decode())
                    if req.remesh_id in handled:
                        req = None
                if req is not None and join_id is not None:
                    # joiner: wait for go, then report done
                    handled.add(req.remesh_id)
                    while not self._get("__remesh__",
                                        f"go_{req.remesh_id}"):
                        if self._stop.wait(0.05):
                            return
                    self._client.put(
                        "__remesh__", f"done_{req.remesh_id}_{rank}",
                        b"1",
                    )
                elif req is not None:
                    handled.add(req.remesh_id)
                    rid = req.remesh_id
                    self._client.put("__remesh__",
                                     f"pause_{rid}_{rank}", b"1")
                    self._client.put("__remesh__",
                                     f"snapshot_{rid}_{rank}", b"1")
                    while True:
                        if self._get("__remesh__", f"abort_{rid}"):
                            self._rc = 73
                            return
                        if self._get("__remesh__", f"go_{rid}"):
                            break
                        if self._stop.wait(0.05):
                            return
                    new_rank = req.new_rank(rank)
                    if new_rank is None:
                        self._client.put(
                            "__remesh__", f"shed_{rid}_{rank}", b"1"
                        )
                        self._rc = REMESH_SHED_CODE
                        return
                    self._client.put(
                        "__remesh__", f"done_{rid}_{new_rank}", b"1"
                    )
                    rank = new_rank
                if self._get("__elastic__",
                             f"hosts_updated_{round_id}"):
                    self._rc = 73
                    return
                if self._stop.wait(0.1):
                    return
        finally:
            try:
                self._client.close()
            except Exception:
                pass


class PhasedDiscovery:
    """Host set changes after a delay (scripted-discovery fake)."""

    def __init__(self, phases):
        self._phases = phases
        self._t0 = time.monotonic()

    def find_available_hosts_and_slots(self):
        t = time.monotonic() - self._t0
        acc = 0.0
        for duration, hosts in self._phases:
            acc += duration
            if t < acc:
                return dict(hosts)
        return dict(self._phases[-1][1])


def _run_driver(driver, factory, spawned):
    """run_rounds in a thread; returns (thread, result holder)."""
    result = {}

    def target():
        try:
            result["rc"] = driver.run_rounds(
                ["true"], worker_factory=factory,
                rendezvous_addr="127.0.0.1",
            )
        except Exception as e:  # surface in the test, not a hang
            result["error"] = e
            result["rc"] = -1

    t = threading.Thread(target=target, daemon=True)
    t.start()
    return t, result


def _test_client(spawned):
    """A KV client built from any spawned worker's env."""
    from horovod_tpu.runner import controller_py

    env = spawned[0].env
    return controller_py.make_client(
        env["HVD_TPU_RENDEZVOUS_ADDR"],
        int(env["HVD_TPU_RENDEZVOUS_PORT"]),
        env["HVD_TPU_SECRET"], rank=-2,
    )


def _await(cond, timeout_s=30, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while not cond():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.05)


class TestDriverRemeshCoordination:
    def _driver(self, phases, min_np, max_np, **kw):
        from horovod_tpu.elastic.discovery import HostManager
        from horovod_tpu.runner.elastic_driver import ElasticDriver

        disco = PhasedDiscovery(phases)
        driver = ElasticDriver(
            HostManager(disco), min_np=min_np, max_np=max_np,
            remesh=True, **kw,
        )
        driver.start_discovery()
        return driver

    def test_shrink_resizes_in_place_without_restart_round(self):
        """3 -> 2 slots: the driver pauses survivors, sheds one worker
        cleanly (exit 75, not blacklisted), and the SAME round
        continues — no respawn round, no checkpoint restore on the hot
        path."""
        from horovod_tpu import metrics

        spawned = []

        def factory(rank, hostname, command, env, **kw):
            w = ScriptedRemeshWorker(rank, hostname, command, env, **kw)
            spawned.append(w)
            return w

        success0 = metrics.get_counter("remesh.driver_success")
        driver = self._driver(
            [(3.0, {"localhost": 3}), (1e9, {"localhost": 2})],
            min_np=2, max_np=3, remesh_timeout_s=20,
        )
        thread, result = _run_driver(driver, factory, spawned)
        _await(lambda: len(spawned) >= 3, msg="3 workers spawned")
        _await(
            lambda: metrics.get_counter("remesh.driver_success")
            > success0,
            timeout_s=40, msg="remesh success",
        )
        client = _test_client(spawned)
        try:
            client.put("__test__", "finish_round_1", b"1")
        finally:
            client.close()
        thread.join(timeout=30)
        driver.stop()
        assert result.get("rc") == 0, result
        assert driver.rounds == 1, "resize must NOT start a new round"
        # exactly one worker shed with the clean code; host not blamed
        assert sorted(w.returncode for w in spawned) == [0, 0, 75]
        assert not driver.host_manager.is_blacklisted("localhost")

    def test_grow_spawns_joiner_into_same_round(self):
        """2 -> 3 slots: a joiner is spawned mid-round with the remesh
        join env and the round continues at the new size."""
        from horovod_tpu import metrics

        spawned = []

        def factory(rank, hostname, command, env, **kw):
            w = ScriptedRemeshWorker(rank, hostname, command, env, **kw)
            spawned.append(w)
            return w

        success0 = metrics.get_counter("remesh.driver_success")
        driver = self._driver(
            [(3.0, {"localhost": 2}), (1e9, {"localhost": 3})],
            min_np=2, max_np=3, remesh_timeout_s=20,
        )
        thread, result = _run_driver(driver, factory, spawned)
        _await(
            lambda: metrics.get_counter("remesh.driver_success")
            > success0,
            timeout_s=40, msg="remesh success",
        )
        joiners = [w for w in spawned
                   if "HVD_TPU_REMESH_JOIN" in w.env]
        assert len(joiners) == 1
        assert joiners[0].env["HVD_TPU_CROSS_SIZE"] == "3"
        client = _test_client(spawned)
        try:
            client.put("__test__", "finish_round_1", b"1")
        finally:
            client.close()
        thread.join(timeout=30)
        driver.stop()
        assert result.get("rc") == 0, result
        assert driver.rounds == 1

    def test_unresponsive_workers_fall_back_to_restart_round(self):
        """Workers that never ack the pause: the attempt times out,
        the driver aborts it and degrades to the classic respawn
        round — bounded fallback, never a wedged job."""
        from horovod_tpu import metrics

        spawned = []

        def factory(rank, hostname, command, env, **kw):
            w = ScriptedRemeshWorker(
                rank, hostname, command, env,
                obey_remesh=False, **kw,
            )
            spawned.append(w)
            return w

        fb0 = metrics.get_counter("remesh.driver_fallback")
        driver = self._driver(
            [(3.0, {"localhost": 3}), (1e9, {"localhost": 2})],
            min_np=2, max_np=3, remesh_timeout_s=2,
        )
        thread, result = _run_driver(driver, factory, spawned)
        _await(
            lambda: metrics.get_counter("remesh.driver_fallback") > fb0,
            timeout_s=40, msg="remesh fallback",
        )
        # fallback publishes the restart signal; workers exit 73 and a
        # second round starts at the new size
        _await(lambda: driver.rounds >= 2, timeout_s=40,
               msg="respawn round")
        client = _test_client(spawned)
        try:
            client.put("__test__", "finish_round_2", b"1")
        finally:
            client.close()
        thread.join(timeout=30)
        driver.stop()
        assert result.get("rc") == 0, result
        assert driver.rounds >= 2

    def test_plan_remesh_world_mappings(self):
        """Survivor/shed/joiner placement math: host-removed shrink
        remaps ranks contiguously; grow keeps survivors' ranks."""
        from horovod_tpu.elastic.discovery import HostManager
        from horovod_tpu.runner import hosts as hosts_mod
        from horovod_tpu.runner.elastic_driver import ElasticDriver

        class _D:
            def find_available_hosts_and_slots(self):
                return {}

        driver = ElasticDriver(HostManager(_D()), min_np=1, remesh=True)

        def slot(host, rank, size):
            return hosts_mod.SlotInfo(
                hostname=host, rank=rank, local_rank=0,
                cross_rank=0, size=size, local_size=1, cross_size=size,
            )

        class _W:
            returncode = None

        # shrink: host b (old rank 1) removed -> survivors remap 0,2->0,1
        old = [slot("a", 0, 3), slot("b", 1, 3), slot("c", 2, 3)]
        survivors, shed, joiners, slots = driver._plan_remesh_world(
            [_W(), _W(), _W()], old, 2, {"a": 1, "c": 1},
        )
        assert survivors == {0: 0, 2: 1}
        assert shed == [1]
        assert joiners == []
        assert [s.hostname for s in slots] == ["a", "c"]
        assert all(s.size == 2 for s in slots)

        # grow: survivors keep ranks, joiner fills the new slot
        old = [slot("a", 0, 2), slot("a", 1, 2)]
        survivors, shed, joiners, slots = driver._plan_remesh_world(
            [_W(), _W()], old, 3, {"a": 3},
        )
        assert survivors == {0: 0, 1: 1}
        assert shed == []
        assert [j.rank for j in joiners] == [2]
        assert slots[2].local_size == 3


# =====================================================================
# The real thing: 4 -> 3 -> 4 process CPU resize (needs a CPU backend
# that supports cross-process computations; skips with the probe's
# reason elsewhere)
# =====================================================================


RESIZE_WORKER = textwrap.dedent("""
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax
    import horovod_tpu as hvd
    from horovod_tpu import sched
    from horovod_tpu.elastic import ArrayState, ShardedZeroState

    hvd.init()
    out = open(os.environ["RESULTS_FILE"]
               + f".{os.environ['HVD_TPU_CROSS_RANK']}."
               + f"{os.getpid()}", "a")

    X = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0],
                     [7.0, 8.0]] * 3)[:12]
    Y = X @ jnp.full((2, 1), 0.5)

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w"] - y) ** 2)

    cfg = sched.SchedConfig(enabled=True, bucket_bytes=32,
                            lowering="flat")
    params = {"w": jnp.full((2, 1), 0.1, jnp.float32)}
    state = ArrayState(params=params, opt_state=None, epoch=0)
    state.register_sharded(
        "zero", ShardedZeroState(state, "params", "opt_state", cfg=cfg)
    )

    tx = optax.adam(0.05)
    meta = {}

    def build_step():
        # rebuild the compiled step for the (possibly new) mesh; the
        # discarded init() builds the bucket layouts without touching
        # the installed opt_state
        meta["step"] = sched.bucketed_zero_step(loss_fn, tx, cfg=cfg)
        meta["step"].init(state.params)

    build_step()
    state.register_reset_callbacks([build_step])
    # Sharded state must exist BEFORE run(): a joiner's remesh fetch
    # happens at wrapper start and needs the fresh-init treedefs.
    state.opt_state = meta["step"].init(state.params)

    @hvd.elastic.run
    def train(state):
        step = meta["step"]
        n = hvd.size()
        if state.opt_state is None:
            state.opt_state = step.init(state.params)
        while state.epoch < 8:
            state.params, state.opt_state, loss = step(
                state.params, state.opt_state, (X[:n], Y[:n])
            )
            state.epoch += 1
            out.write(f"epoch={state.epoch} size={hvd.size()} "
                      f"loss={float(loss):.8f}\\n")
            out.flush()
            time.sleep(0.4)
            state.commit()
        return state.epoch

    final = train(state)
    out.write(f"done epoch={final} size={hvd.size()}\\n")
    out.close()
""")


@pytest.mark.multiproc
@pytest.mark.faults
def test_process_resize_4_3_4(tmp_path):
    """Kill-and-resize end to end with real worker processes: a
    seed-reproducible fault plan shrinks the world 4 -> 3 and grows it
    back 3 -> 4; training resumes in place each time (driver stays in
    round 1) and the elastic event log records every remesh phase."""
    from horovod_tpu import events, faults
    from horovod_tpu.elastic.discovery import FixedHosts, HostManager
    from horovod_tpu.runner.elastic_driver import ElasticDriver

    script = tmp_path / "worker.py"
    script.write_text(RESIZE_WORKER)
    results_file = str(tmp_path / "results")
    event_log = str(tmp_path / "events.jsonl")

    faults.set_plan(
        "discovery.resize:resize_to:np=3,nth=8;"
        "discovery.resize:resize_to:np=4,nth=20,times=0"
    )
    events.set_event_log(events.EventLog(event_log))
    try:
        driver = ElasticDriver(
            HostManager(FixedHosts({"localhost": 4})),
            min_np=3, max_np=4, remesh=True, remesh_timeout_s=60,
        )
        driver.start_discovery()
        rc = driver.run_rounds(
            [sys.executable, str(script)],
            extra_env={
                "RESULTS_FILE": results_file,
                "PYTHONPATH": REPO + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            },
        )
        assert rc == 0
    finally:
        faults.set_plan(None)
        events.set_event_log(None)

    logged = events.read_events(event_log)
    names = [e["event"] for e in logged]
    assert events.REMESH_START in names
    assert events.REMESH_PHASE in names
    lines = []
    for fn in os.listdir(tmp_path):
        if fn.startswith("results."):
            lines += (tmp_path / fn).read_text().splitlines()
    assert any(l.startswith("done epoch=8") for l in lines)
    sizes = {
        int(l.split("size=")[1].split()[0])
        for l in lines if l.startswith("epoch=")
    }
    assert 3 in sizes or events.REMESH_OK in names
