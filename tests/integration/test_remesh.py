"""In-process re-mesh: the probe findings as a regression test.

Evidence base: ``tools/probe_remesh.py`` → the elastic driver's
respawn-per-round rationale plus the experimental
``hvd.elastic.reinit_world`` survivor path."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

pytestmark = pytest.mark.integration

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PYTHONPATH": REPO,
}


def test_probe_report_structure():
    """The committed findings artifact matches reality on this machine."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "probe_remesh.py")],
        capture_output=True, text=True, timeout=500,
        env={**os.environ, **_ENV},
    )
    assert proc.returncode == 0, proc.stderr[-400:]
    report = json.loads(proc.stdout)
    assert report["A_single_process_subset_remesh"]["works"]
    assert not report["B_multiprocess_world_resize"]["works"]
    assert report["B_multiprocess_world_resize"]["works_after_backend_reset"]


SURVIVOR = textwrap.dedent("""
    import os, sys
    import numpy as np
    import jax

    port = os.environ["PROBE_PORT"]
    rank = int(os.environ["PROBE_RANK"])
    os.environ["HVD_TPU_COORDINATOR_ADDR"] = f"127.0.0.1:{port}"
    os.environ["HVD_TPU_CROSS_SIZE"] = "2"
    os.environ["HVD_TPU_CROSS_RANK"] = str(rank)
    import horovod_tpu as hvd

    hvd.init()
    assert hvd.process_count() == 2
    # both ranks train happily...
    out = np.asarray(hvd.allreduce(
        np.ones((len(jax.local_devices()), 2), np.float32), op=hvd.Sum
    ))
    if rank == 1:
        sys.exit(0)  # ...then the peer dies

    # survivor re-meshes IN-PROCESS to a single-process world
    import horovod_tpu.elastic as elastic

    elastic.reinit_world()
    assert hvd.process_count() == 1
    y = np.asarray(hvd.allreduce(
        np.ones((hvd.size(), 3), np.float32), op=hvd.Sum
    ))
    assert y[0, 0] == float(hvd.size())
    print("SURVIVOR_REMESH_OK size=", hvd.size())
""")


@pytest.mark.multiproc
def test_survivor_reinit_world_in_process():
    from horovod_tpu.runner.launch import free_port

    port = free_port()
    env = {**os.environ, **_ENV, "PROBE_PORT": str(port)}
    p1 = subprocess.Popen(
        [sys.executable, "-c", SURVIVOR],
        env={**env, "PROBE_RANK": "1"},
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    p0 = subprocess.run(
        [sys.executable, "-c", SURVIVOR],
        env={**env, "PROBE_RANK": "0"},
        capture_output=True, text=True, timeout=300,
    )
    p1.wait(timeout=60)
    out = p0.stdout + p0.stderr
    assert p0.returncode == 0, out[-800:]
    assert "SURVIVOR_REMESH_OK" in out


def test_reinit_world_validates_partial_triple():
    import horovod_tpu.elastic as elastic

    with pytest.raises(ValueError, match="num_processes"):
        elastic.reinit_world(coordinator_address="10.0.0.5:1234")
