"""End-to-end example runs (reference ``test/integration`` tier: real
subprocess jobs).  Each example executes with tiny settings on the
8-device virtual CPU mesh."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.integration

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _run_example(script, *args, timeout=420):
    env = dict(os.environ)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_PLATFORMS": "cpu",
        # PYTHONPATH is the repo ONLY — an inherited accelerator-plugin
        # site dir (e.g. the axon TPU relay) would register a PJRT
        # backend whose init dials remote hardware and can hang for
        # minutes, even with JAX_PLATFORMS=cpu in the env.
        "PYTHONPATH": REPO,
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        env=env, capture_output=True, text=True, timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"{script} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    return proc.stdout


def test_mnist_example():
    out = _run_example("mnist.py", "--epochs", "1", "--batch-size", "8",
                       "--num-samples", "256")
    assert "loss" in out.lower()


def test_torch_mnist_example():
    pytest.importorskip("torch")
    out = _run_example("torch_mnist.py", "--epochs", "1",
                       "--batch-size", "16", "--num-samples", "256")
    assert "loss" in out.lower()


def test_lightning_mnist_example(tmp_path):
    pytest.importorskip("torch")
    out = _run_example("lightning_mnist.py", "--epochs", "1",
                       "--batch-size", "32", "--num-samples", "256",
                       "--store", str(tmp_path / "ls"))
    assert "val_loss" in out


def test_estimator_mnist_example(tmp_path):
    pytest.importorskip("torch")
    out = _run_example("estimator_mnist.py", "--epochs", "1",
                       "--store", str(tmp_path / "es"), timeout=600)
    assert "keras-style history" in out
    assert "resumed for 1 new epoch(s)" in out


def test_tf2_keras_mnist_example():
    pytest.importorskip("tensorflow")
    out = _run_example("tf2_keras_mnist.py", "--epochs", "1",
                       "--batch-size", "16", "--num-samples", "256",
                       timeout=600)
    assert "loss" in out.lower()


def test_tf2_keras_mnist_fit_mode_example():
    """model.fit + DistributedOptimizer(backward_passes_per_step=2) +
    BroadcastGlobalVariablesCallback — the reference keras recipe,
    exercising the compiled-fit (tf.cond) aggregation path."""
    pytest.importorskip("tensorflow")
    out = _run_example("tf2_keras_mnist.py", "--use-fit", "--epochs", "1",
                       "--batch-size", "16", "--num-samples", "256",
                       "--backward-passes-per-step", "2", timeout=600)
    assert "final loss" in out


def test_process_sets_example():
    out = _run_example("process_sets.py")
    assert "even-team avg: 3.0" in out
    assert "odd-team avg: 4.0" in out


def test_synthetic_benchmark_example():
    out = _run_example(
        "synthetic_benchmark.py", "--model", "resnet50",
        "--image-size", "32", "--batch-size", "2",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1",
    )
    assert "Img/sec per chip" in out


def test_imagenet_resnet50_example():
    """North-star example end to end (tiny shapes: full ResNet-50 depth
    at 32px, one epoch) — compile dominates, hence the long timeout."""
    out = _run_example(
        "imagenet_resnet50.py", "--epochs", "1", "--batch-size", "2",
        "--image-size", "32", "--num-samples", "32",
        "--warmup-epochs", "1", timeout=560,
    )
    assert "loss" in out.lower()


def test_embedding_sparse_example():
    out = _run_example("embedding_sparse.py", "--steps", "120",
                       "--batch-size", "16", "--lr", "2.0",
                       "--num-samples", "32768")
    lines = [l for l in out.splitlines() if l.startswith("step")]
    assert lines, out
    first = float(lines[0].split()[3])
    last = float(lines[-1].split()[3])
    assert last < first, (first, last)
    assert "sparse reduction" in lines[-1]


def test_embedding_sparse_as_dense_example():
    out = _run_example("embedding_sparse.py", "--steps", "10",
                       "--batch-size", "8", "--num-samples", "2048",
                       "--sparse-as-dense")
    assert "dense reduction" in out


def test_fsdp_gpt_example():
    out = _run_example("fsdp_gpt.py", "--steps", "20")
    lines = [l for l in out.splitlines() if l.startswith("step")]
    assert lines
    first = float(lines[0].split()[-1])
    last = float(lines[-1].split()[-1])
    assert last < first, (first, last)
    assert "gathered eval logits" in out


def test_gpt_pretrain_example():
    out = _run_example(
        "gpt_pretrain.py", "--dp", "2", "--sp", "2", "--tp", "2",
        "--steps", "3", "--seq-per-sp", "32",
    )
    assert "mesh dp2/sp2/tp2" in out


@pytest.mark.multiproc
def test_spark_elastic_example():
    out = _run_example(
        "spark_elastic.py", "--local", "--simulate-loss", "--epochs", "5",
    )
    import re

    # round >= 2 (recovery happened); the exact count is timing-dependent
    assert re.search(r"job finished on round [2-9] with 2 worker\(s\)", out)
    assert "rank 1:" in out


def test_gpt_pretrain_packed_example():
    out = _run_example(
        "gpt_pretrain.py", "--dp", "4", "--tp", "2", "--attn", "flash",
        "--packed", "--steps", "3", "--seq-per-sp", "64",
    )
    assert "efficiency" in out
    assert "mesh dp4/sp1/tp2" in out
