"""Multi-process collective fuzz: a seeded random op sequence executed
by 2 real processes, checked against numpy (reference analog: the
randomized sweeps in ``test/parallel/test_torch.py`` run under real
MPI workers rather than a single process).

Every process derives the SAME global arrays from the seed, submits its
process-local rows, and checks the returned rows against the numpy
reduction of the global array — exercising ordering, dtype handling,
and the multi-controller dispatch path under a long mixed workload.
"""

import sys

import cloudpickle
import numpy as np
import pytest

import horovod_tpu.runner as runner

pytestmark = pytest.mark.integration

N_OPS = 24


def _worker():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    size = hvd.size()
    me = hvd.process_rank()
    nproc = hvd.process_count()
    rows_per_proc = size // nproc
    lo = me * rows_per_proc
    hi = lo + rows_per_proc

    rng = np.random.RandomState(1234)  # same stream on every process
    failures = []
    for i in range(N_OPS):
        op = rng.choice(["allreduce_avg", "allreduce_sum", "allgather",
                         "broadcast", "alltoall"])
        dtype = rng.choice([np.float32, np.int32])
        cols = int(rng.randint(1, 6))
        if dtype == np.float32:
            full = rng.rand(size, size, cols).astype(dtype)
        else:
            full = rng.randint(0, 9, (size, size, cols)).astype(dtype)
        root = int(rng.randint(0, size))
        local = full[lo:hi]

        if op == "allreduce_avg":
            out = np.asarray(hvd.allreduce(local, average=True))
            want = full.astype(np.float64).mean(axis=0)
            if dtype == np.int32:
                want = np.trunc(want)
            want = np.broadcast_to(want, local.shape)
        elif op == "allreduce_sum":
            out = np.asarray(hvd.allreduce(local, op=hvd.Sum))
            want = np.broadcast_to(
                full.astype(np.float64).sum(axis=0), local.shape
            )
        elif op == "allgather":
            out = np.asarray(hvd.allgather(local))
            want = np.broadcast_to(
                full.reshape(size * size, cols),
                (rows_per_proc, size * size, cols),
            )
        elif op == "broadcast":
            out = np.asarray(hvd.broadcast(local, root_rank=root))
            want = np.broadcast_to(full[root], local.shape)
        else:  # alltoall: even split, row r chunk j -> row j
            out = np.asarray(hvd.alltoall(local))
            want = full.transpose(1, 0, 2)[lo:hi]
        if not np.allclose(out.astype(np.float64), want, rtol=1e-4,
                           atol=1e-4):
            failures.append((i, str(op), str(np.dtype(dtype))))
    hvd.shutdown()
    return failures


@pytest.mark.multiproc
def test_two_process_fuzz():
    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(_worker, np=2, use_cpu_devices=True)
    assert results[0] == [] and results[1] == [], results
