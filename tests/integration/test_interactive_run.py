"""Integration: the programmatic run API with real multi-process
collectives over the CPU backend.

Reference analog: ``test/integration/test_interactiverun.py`` +
``test_static_run.py`` — actually spawning workers on localhost and
running collectives through the launcher's rendezvous.
"""

import os
import sys

import numpy as np
import pytest

import cloudpickle

import horovod_tpu.runner as runner

# ship the worker functions by value: workers can't import this module
cloudpickle.register_pickle_by_value(sys.modules[__name__])

pytestmark = pytest.mark.integration


def _world_info():
    import horovod_tpu as hvd

    hvd.init()
    return {
        "size": hvd.size(),
        "rank": hvd.rank(),
        "cross_rank": hvd.cross_rank(),
        "cross_size": hvd.cross_size(),
        "process_count": hvd.process_count(),
    }


def _allreduce_local():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    # process-local row (the reference's per-process call shape)
    x = np.full((1, 4), float(hvd.process_rank() + 1), np.float32)
    y = np.asarray(hvd.allreduce(x, op=hvd.Sum))
    return y.tolist()


def _broadcast_object_value():
    import horovod_tpu as hvd

    hvd.init()
    obj = {"vec": [1, 2, 3]} if hvd.process_rank() == 0 else None
    return hvd.broadcast_object(obj, root_rank=0)


@pytest.mark.multiproc
def test_run_world_topology():
    results = runner.run(_world_info, np=2, use_cpu_devices=True)
    assert len(results) == 2
    assert all(r["size"] == 2 for r in results)
    assert sorted(r["rank"] for r in results) == [0, 1]
    assert all(r["process_count"] == 2 for r in results)


@pytest.mark.multiproc
def test_run_allreduce_across_processes():
    results = runner.run(_allreduce_local, np=2, use_cpu_devices=True)
    # sum of rows [1,...] and [2,...] = [3,...] on both ranks
    for r in results:
        np.testing.assert_allclose(np.asarray(r), 3.0)


@pytest.mark.multiproc
def test_run_broadcast_object():
    results = runner.run(_broadcast_object_value, np=2, use_cpu_devices=True)
    assert results[0] == results[1] == {"vec": [1, 2, 3]}


def _uneven_join():
    """Rank 0 exhausts its data first and joins early; rank 1 keeps
    training for a while, then joins.  Both must learn rank 1 joined
    last (reference join semantics, operations.cc:1714)."""
    import time

    import horovod_tpu as hvd

    hvd.init()
    if hvd.process_rank() == 1:
        time.sleep(1.0)  # "still has batches"
    return hvd.join()


@pytest.mark.multiproc
def test_run_true_join_last_rank():
    results = runner.run(_uneven_join, np=2, use_cpu_devices=True)
    # process 1 joined last; its (only) device rank is world rank 1
    assert results[0] == results[1] == 1


def _staggered_joins_rank0_last():
    """Two join epochs with different stragglers.  Epoch 1: rank 0
    joins LAST — the answer must be 0, NOT the degenerate size-1
    default, proving the KV arrival order is really consulted
    (VERDICT r3 weak-5).  Epoch 2: rank 1 is last."""
    import time

    import horovod_tpu as hvd

    hvd.init()
    if hvd.process_rank() == 0:
        time.sleep(1.2)  # rank 0 still has batches in epoch 1
    first = hvd.join()
    if hvd.process_rank() == 1:
        time.sleep(1.2)  # roles swap for epoch 2
    second = hvd.join()
    return [first, second]


@pytest.mark.multiproc
def test_run_staggered_joins_specific_last_rank():
    results = runner.run(
        _staggered_joins_rank0_last, np=2, use_cpu_devices=True
    )
    # both processes agree, per epoch, on the true straggler
    assert results[0] == results[1] == [0, 1], results


def _multi_collective_suite():
    """One worker body exercising every collective across 2 real
    processes (the reference's test_static_run-style sweep)."""
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.process_rank()
    out = {}

    x = np.full((1, 3), float(r + 1), np.float32)
    out["allgather"] = np.asarray(hvd.allgather(x)).tolist()
    out["broadcast"] = np.asarray(hvd.broadcast(x, root_rank=1)).tolist()
    out["reducescatter"] = np.asarray(
        hvd.reducescatter(np.full((1, 2, 3), float(r + 1), np.float32))
    ).tolist()
    a2a = np.asarray(
        hvd.alltoall(np.full((1, 2, 3), float(r + 1), np.float32))
    )
    out["alltoall"] = a2a.tolist()
    out["allgather_v"] = np.asarray(
        hvd.allgather_v([np.full((r + 1, 2), float(r), np.float32)])
    ).tolist()
    out["grouped"] = [
        np.asarray(t).tolist()
        for t in hvd.grouped_allreduce(
            [x, np.full((1, 1), float(r), np.float32)], op=hvd.Sum
        )
    ]
    return out


@pytest.mark.multiproc
def test_run_collective_sweep_across_processes():
    results = runner.run(_multi_collective_suite, np=2, use_cpu_devices=True)
    r0, r1 = results
    # allgather: per-rank (3,) tensors concatenate to (6,)
    assert np.asarray(r0["allgather"]).shape == (1, 6)
    np.testing.assert_allclose(
        np.asarray(r0["allgather"])[0], [1, 1, 1, 2, 2, 2]
    )
    np.testing.assert_allclose(r0["allgather"], r1["allgather"])
    # broadcast from rank 1: everyone holds 2.0
    np.testing.assert_allclose(np.asarray(r0["broadcast"]), 2.0)
    np.testing.assert_allclose(np.asarray(r1["broadcast"]), 2.0)
    # reducescatter: rank r gets row r of the summed (2,3) payload = 3.0
    np.testing.assert_allclose(np.asarray(r0["reducescatter"]), 3.0)
    np.testing.assert_allclose(np.asarray(r1["reducescatter"]), 3.0)
    # alltoall: rank r's row j = rank j's chunk r
    np.testing.assert_allclose(np.asarray(r0["alltoall"])[0, 0], 1.0)
    np.testing.assert_allclose(np.asarray(r0["alltoall"])[0, 1], 2.0)
    # ragged allgather: 1 row from rank 0 (value 0) + 2 rows from rank 1
    v = np.asarray(r0["allgather_v"])
    assert v.shape == (3, 2)
    np.testing.assert_allclose(v[:, 0], [0.0, 1.0, 1.0])
    np.testing.assert_allclose(r0["allgather_v"], r1["allgather_v"])
    # grouped allreduce sums both tensors atomically
    np.testing.assert_allclose(np.asarray(r0["grouped"][0]), 3.0)
    np.testing.assert_allclose(np.asarray(r0["grouped"][1]), 1.0)


def _consistency_ok():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    x = np.ones((1, 4), np.float32)
    return float(np.asarray(hvd.allreduce(x, op=hvd.Sum))[0, 0])


def _consistency_mismatch():
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.exceptions import HorovodTpuError

    hvd.init()
    # rank 1 submits a different dtype — the wire-Request cross-check
    # must catch it before dispatch (reference controller.cc validation)
    dtype = np.float32 if hvd.process_rank() == 0 else np.int32
    try:
        hvd.allreduce(np.ones((1, 4), dtype), op=hvd.Sum)
        return "no-error"
    except HorovodTpuError as e:
        return "caught" if "consistency" in str(e) else f"wrong: {e}"


@pytest.mark.multiproc
def test_run_consistency_check_modes():
    env = {"HVD_TPU_CONSISTENCY_CHECK": "1"}
    ok = runner.run(_consistency_ok, np=2, use_cpu_devices=True,
                    extra_env=env)
    assert ok == [2.0, 2.0]
    res = runner.run(_consistency_mismatch, np=2, use_cpu_devices=True,
                     extra_env=env)
    assert res == ["caught", "caught"]


def test_run_worker_failure_raises():
    def boom():
        raise RuntimeError("worker exploded")

    with pytest.raises(RuntimeError, match="exploded|exited"):
        runner.run(boom, np=2, use_cpu_devices=True)
