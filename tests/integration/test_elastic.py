"""Elastic integration: real worker processes, membership change
mid-training, state carried across rounds.

Reference analog: ``test/integration/elastic_common.py`` +
``test_elastic_torch.py`` — scripted discovery emitting different host
lists over time, real elastic jobs, asserting world sizes and state
continuity per round.
"""

import os
import sys
import textwrap
import threading
import time

import pytest

from horovod_tpu import faults, metrics
from horovod_tpu.elastic.discovery import HostDiscovery, HostManager
from horovod_tpu.runner.elastic_driver import ElasticDriver
from horovod_tpu.utils.retry import RetryPolicy

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
WORKER_ENV = {
    "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
}

pytestmark = pytest.mark.integration

WORKER_SCRIPT = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu.elastic import ObjectState

    hvd.init()
    out = open(os.environ["RESULTS_FILE"] + f".{os.environ['HVD_TPU_CROSS_RANK']}", "a")

    state = ObjectState(epoch=0)

    @hvd.elastic.run
    def train(state):
        while state.epoch < 6:
            time.sleep(0.8)  # one "epoch" of work
            state.epoch += 1
            print(f"epoch {state.epoch} world {hvd.size()}", flush=True)
            out.write(f"round={os.environ['HVD_TPU_ELASTIC_ROUND']} "
                      f"epoch={state.epoch} size={hvd.size()}\\n")
            out.flush()
            state.commit()
        return state.epoch

    import time
    final = train(state)
    out.write(f"done epoch={final}\\n")
    out.close()
    """
)


class ScriptedDiscovery(HostDiscovery):
    """Host set changes after a delay (the scripted-discovery fake)."""

    def __init__(self, phases):
        # phases: list of (duration_s, {host: slots}); last phase persists
        self._phases = phases
        self._t0 = time.monotonic()

    def find_available_hosts_and_slots(self):
        t = time.monotonic() - self._t0
        acc = 0.0
        for duration, hosts in self._phases:
            acc += duration
            if t < acc:
                return dict(hosts)
        return dict(self._phases[-1][1])


@pytest.mark.multiproc
def test_elastic_membership_change(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)
    results_file = str(tmp_path / "results")

    discovery = ScriptedDiscovery([
        (3.0, {"localhost": 2}),
        (1e9, {"localhost": 3}),  # scale up after 3s
    ])
    driver = ElasticDriver(HostManager(discovery), min_np=2, max_np=4)
    driver.start_discovery()
    rc = driver.run_rounds(
        [sys.executable, str(script)],
        extra_env={"RESULTS_FILE": results_file, **WORKER_ENV},
    )
    assert rc == 0
    assert driver.rounds >= 2, "membership change should have forced a new round"

    # parse per-rank logs: epochs must be monotonic across rounds (state
    # survived the restart) and the final round must run at size 3
    lines = []
    for fn in os.listdir(tmp_path):
        if fn.startswith("results."):
            lines += (tmp_path / fn).read_text().splitlines()
    assert any(l.startswith("done epoch=6") for l in lines)
    by_round = {}
    for l in lines:
        if l.startswith("round="):
            parts = dict(kv.split("=") for kv in l.split())
            by_round.setdefault(int(parts["round"]), []).append(
                (int(parts["epoch"]), int(parts["size"]))
            )
    first_round = min(by_round)
    last_round = max(by_round)
    assert first_round != last_round
    assert all(s == 2 for _, s in by_round[first_round])
    assert all(s == 3 for _, s in by_round[last_round])
    max_epoch_first = max(e for e, _ in by_round[first_round])
    min_epoch_last = min(e for e, _ in by_round[last_round])
    assert min_epoch_last >= max_epoch_first, (
        f"state lost across rounds: round {first_round} reached "
        f"{max_epoch_first}, round {last_round} restarted at {min_epoch_last}"
    )


COST_WORKER_SCRIPT = textwrap.dedent(
    """
    import os, sys, time
    t_start = time.perf_counter()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.elastic import ObjectState

    hvd.init()
    params = {"w": jnp.zeros((32, 32)), "b": jnp.zeros((32,))}
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))

    def loss_fn(p, batch):
        h = jnp.tanh(batch @ p["w"] + p["b"])
        return jnp.mean((h @ p["w"]) ** 2)

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)
    batch = jnp.ones((4 * hvd.size(), 32))
    state = ObjectState(epoch=0)

    first_step = [True]

    @hvd.elastic.run
    def train(state):
        global params, opt_state
        while state.epoch < 4:
            p2, o2, loss = step(params, opt_state, batch)
            params, opt_state = p2, o2
            float(loss)
            if first_step[0]:
                first_step[0] = False
                # init -> first completed step = the round's restart cost
                # (fresh process per round, so this fires once per round)
                cost = time.perf_counter() - t_start
                with open(os.environ["RESULTS_FILE"]
                          + f".{os.environ['HVD_TPU_CROSS_RANK']}", "a") as fh:
                    fh.write(f"round={os.environ['HVD_TPU_ELASTIC_ROUND']} "
                             f"restart_cost_s={cost:.3f}\\n")
            time.sleep(0.4)
            state.epoch += 1
            state.commit()
        return state.epoch

    train(state)
    """
)


@pytest.mark.multiproc
def test_elastic_restart_cost_bounded(tmp_path):
    """Measures the full cost of a membership-change restart (process
    respawn + hvd re-init + recompile + first step) and bounds the
    second round via the persistent XLA compilation cache (reference
    concern: elastic reset cost; TPU twist: recompilation dominates, so
    JAX_COMPILATION_CACHE_DIR turns round-2 compiles into cache reads)."""
    script = tmp_path / "worker.py"
    script.write_text(COST_WORKER_SCRIPT)
    results_file = str(tmp_path / "results")
    cache_dir = str(tmp_path / "xla_cache")

    discovery = ScriptedDiscovery([
        (2.5, {"localhost": 2}),
        (1e9, {"localhost": 3}),
    ])
    driver = ElasticDriver(HostManager(discovery), min_np=2, max_np=4)
    driver.start_discovery()
    rc = driver.run_rounds(
        [sys.executable, str(script)],
        extra_env={
            "RESULTS_FILE": results_file,
            "JAX_COMPILATION_CACHE_DIR": cache_dir,
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
            "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
            **WORKER_ENV,
        },
    )
    assert rc == 0
    assert driver.rounds >= 2

    costs = {}
    for fn in os.listdir(tmp_path):
        if fn.startswith("results."):
            for l in (tmp_path / fn).read_text().splitlines():
                parts = dict(kv.split("=") for kv in l.split())
                rnd = int(parts["round"])
                costs.setdefault(rnd, []).append(
                    float(parts["restart_cost_s"])
                )
    assert len(costs) >= 2, f"need costs from >=2 rounds, got {costs}"
    first, last = min(costs), max(costs)
    c1 = max(costs[first])
    c2 = max(costs[last])
    print(f"elastic restart cost: round{first}={c1:.2f}s "
          f"round{last}={c2:.2f}s (cache dir {cache_dir})")
    # The restart (world resize!) must not cost more than the cold
    # start plus slack: compile work is bounded by the persistent
    # cache.  The slack is generous because this is wall-clock on a
    # shared box — under a fully loaded single-core host (e.g. the
    # whole matrix running) scheduler noise alone can double a round.
    assert c2 <= c1 * 3.0 + 5.0, (first, c1, last, c2)


def test_elastic_worker_failure_blacklists_and_continues(tmp_path):
    """A worker that dies is handled: the driver starts a new round
    (reference fault-tolerance-without-scaling case)."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(
        """
        import os, sys, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
        import horovod_tpu as hvd
        hvd.init()
        round_id = int(os.environ["HVD_TPU_ELASTIC_ROUND"])
        rank = int(os.environ["HVD_TPU_CROSS_RANK"])
        host = os.environ["HVD_TPU_HOSTNAME"]
        marker = os.environ["RESULTS_FILE"] + f".round{round_id}.rank{rank}"
        open(marker, "w").write(f"size={hvd.size()} host={host}\\n")
        if round_id == 1 and host == "127.0.0.1":
            os._exit(7)  # simulated crash of the 127.0.0.1 "host"
        time.sleep(1.0)
        """
    ))
    results_file = str(tmp_path / "marks")
    discovery = ScriptedDiscovery([(1e9, {"localhost": 1, "127.0.0.1": 1})])
    driver = ElasticDriver(HostManager(discovery), min_np=1, max_np=2)
    driver.start_discovery()
    rc = driver.run_rounds(
        [sys.executable, str(script)],
        extra_env={"RESULTS_FILE": results_file, **WORKER_ENV},
    )
    assert rc == 0
    assert driver.rounds == 2
    marks = sorted(os.listdir(tmp_path))
    assert any("round2" in m for m in marks)


# ---- deterministic fault injection (HVD_TPU_FAULT_PLAN) ---------------

# This worker exercises the real worker-side fault-tolerance plumbing
# (KV rendezvous + heartbeats + host-update notification + cross-round
# state persistence via elastic_worker) without multi-process jax
# collectives — the CPU backend in CI cannot run those (see
# test_elastic_membership_change, which degrades for the same reason),
# and the subject under test here is the DRIVER's failure handling.
FAULT_WORKER_SCRIPT = textwrap.dedent(
    """
    import os, pickle, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    from horovod_tpu import faults
    from horovod_tpu.runner import elastic_worker

    round_id = int(os.environ["HVD_TPU_ELASTIC_ROUND"])
    rank = int(os.environ["HVD_TPU_CROSS_RANK"])
    size = int(os.environ["HVD_TPU_CROSS_SIZE"])
    host = os.environ["HVD_TPU_HOSTNAME"]

    class Flag:
        updated = False
        def on_hosts_updated(self, ts, res):
            self.updated = True

    flag = Flag()
    mgr = elastic_worker.get_notification_manager()
    mgr.register_listener(flag)
    mgr.init()  # KV connect (retried) + notification poll + heartbeats

    blob = mgr.load_state_blob()
    epoch = pickle.loads(blob) if blob else 0
    out = open(os.environ["RESULTS_FILE"] + f".{rank}", "a")
    target = int(os.environ.get("TARGET_EPOCHS", "10"))
    while epoch < target:
        time.sleep(float(os.environ.get("EPOCH_SECS", "0.5")))
        # the scripted failure site: the env fault plan decides if,
        # when, and on which host/round/rank this fires
        faults.inject("worker.step", rank=rank, round=round_id,
                      host=host, epoch=epoch)
        epoch += 1
        out.write(f"round={round_id} epoch={epoch} size={size}\\n")
        out.flush()
        mgr.save_state_blob(pickle.dumps(epoch))
        if flag.updated:
            out.write(f"restart round={round_id}\\n")
            out.close()
            sys.stdout.flush()
            os._exit(73)  # RESTART_CODE: ack the membership change
    out.write(f"done epoch={epoch}\\n")
    out.close()
    mgr.close()
    """
)


@pytest.mark.faults
def test_injected_crash_blacklist_cooldown_recovery(tmp_path):
    """The acceptance-criteria scenario: a seeded fault plan crashes the
    127.0.0.1 worker mid-round-1; the driver blacklists the host and
    restarts at reduced size; the blacklist cooldown expires while the
    survivors train on; discovery re-admits the host and the final round
    runs at full size to completion — with the whole story visible in
    the metrics counters."""
    metrics.reset_counters()
    script = tmp_path / "worker.py"
    script.write_text(FAULT_WORKER_SCRIPT)
    results_file = str(tmp_path / "results")

    discovery = ScriptedDiscovery([(1e9, {"localhost": 1, "127.0.0.1": 1})])
    driver = ElasticDriver(
        HostManager(discovery, cooldown_s=2.0, cooldown_max_s=8.0),
        min_np=1, max_np=2,
    )
    driver.start_discovery()
    rc = driver.run_rounds(
        [sys.executable, str(script)],
        extra_env={
            "RESULTS_FILE": results_file,
            "TARGET_EPOCHS": "10",
            "EPOCH_SECS": "0.6",
            "HVD_TPU_FAULT_PLAN":
                "worker.step:crash:host=127.0.0.1,round=1,nth=1,code=5",
            **WORKER_ENV,
        },
    )
    assert rc == 0
    assert driver.rounds >= 3, (
        "expected crash round + degraded round + recovered round, got "
        f"{driver.rounds}"
    )

    lines = []
    for fn in os.listdir(tmp_path):
        if fn.startswith("results."):
            lines += (tmp_path / fn).read_text().splitlines()
    assert any(l.startswith("done epoch=10") for l in lines)
    sizes_by_round = {}
    for l in lines:
        if l.startswith("round="):
            parts = dict(kv.split("=") for kv in l.split())
            sizes_by_round.setdefault(int(parts["round"]), set()).add(
                int(parts["size"])
            )
    # degraded round at size 1 while 127.0.0.1 cooled down, then
    # recovery back to size 2
    assert any(s == {1} for s in sizes_by_round.values()), sizes_by_round
    assert sizes_by_round[max(sizes_by_round)] == {2}, sizes_by_round

    got = metrics.get_counters("elastic.")
    assert got.get("elastic.worker_crash", 0) >= 1, got
    assert got.get("elastic.blacklist", 0) >= 1, got
    assert got.get("elastic.unblacklist", 0) >= 1, got
    assert not driver.host_manager.is_blacklisted("127.0.0.1")
    assert driver.host_manager.failure_count("127.0.0.1") == 1


@pytest.mark.faults
def test_injected_hang_detected_by_heartbeat(tmp_path):
    """A worker whose heartbeat freezes (process alive, no progress
    signal) is declared hung by the driver's health monitor, terminated,
    and its host blacklisted — counted as a hang, not a crash."""
    metrics.reset_counters()
    script = tmp_path / "worker.py"
    script.write_text(FAULT_WORKER_SCRIPT)
    results_file = str(tmp_path / "results")

    discovery = ScriptedDiscovery([(1e9, {"localhost": 1, "127.0.0.1": 1})])
    driver = ElasticDriver(
        HostManager(discovery, cooldown_s=300.0),
        min_np=1, max_np=2, hang_timeout_s=2.5,
    )
    driver.start_discovery()
    rc = driver.run_rounds(
        [sys.executable, str(script)],
        extra_env={
            "RESULTS_FILE": results_file,
            "TARGET_EPOCHS": "30",
            "EPOCH_SECS": "0.4",
            # rank 0 lands on 127.0.0.1 (hosts sort lexically); its
            # heartbeat thread freezes in round 1 after registering
            "HVD_TPU_FAULT_PLAN":
                "worker.heartbeat:hang:rank=0,round=1,secs=120",
            **WORKER_ENV,
        },
    )
    assert rc == 0
    got = metrics.get_counters("elastic.")
    assert got.get("elastic.worker_hang", 0) == 1, got
    assert got.get("elastic.worker_crash", 0) == 0, got
    assert driver.host_manager.is_blacklisted("127.0.0.1")
    lines = []
    for fn in os.listdir(tmp_path):
        if fn.startswith("results."):
            lines += (tmp_path / fn).read_text().splitlines()
    assert any(l.startswith("done epoch=30") for l in lines)


@pytest.mark.faults
def test_spawn_flake_absorbed_by_retry(tmp_path):
    """A transient spawn failure (injected in the DRIVER process at the
    driver.spawn site) is retried instead of blacklisting the host."""
    metrics.reset_counters()
    faults.set_plan("driver.spawn:error:nth=1")
    try:
        script = tmp_path / "worker.py"
        script.write_text("import sys; sys.exit(0)\n")
        discovery = ScriptedDiscovery([(1e9, {"localhost": 1})])
        driver = ElasticDriver(
            HostManager(discovery), min_np=1, max_np=1,
            spawn_retry=RetryPolicy(
                max_attempts=2, base_delay_s=0.0, name="elastic.spawn"
            ),
        )
        driver.start_discovery()
        rc = driver.run_rounds([sys.executable, str(script)],
                               extra_env=dict(WORKER_ENV))
    finally:
        faults.set_plan(None)
    assert rc == 0
    assert driver.rounds == 1  # the flake cost a retry, not a round
    assert not driver.host_manager.is_blacklisted("localhost")
    assert metrics.get_counter("retry.elastic.spawn.retries") == 1
    assert metrics.get_counter("faults.injected.driver.spawn.error") == 1


@pytest.mark.faults
def test_round_watchdog_restarts_stuck_round(tmp_path):
    """round_timeout_s bounds a round that makes no progress at all
    (e.g. every worker stuck before hvd.init); the watchdog restarts it
    rather than hanging the job forever."""
    metrics.reset_counters()
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(
        """
        import os, sys, time
        if int(os.environ["HVD_TPU_ELASTIC_ROUND"]) == 1:
            time.sleep(60)
        sys.exit(0)
        """
    ))
    discovery = ScriptedDiscovery([(1e9, {"localhost": 1})])
    driver = ElasticDriver(
        HostManager(discovery), min_np=1, max_np=1,
        round_timeout_s=2.0, cooldown_s=0.1,
    )
    driver.start_discovery()
    t0 = time.monotonic()
    rc = driver.run_rounds([sys.executable, str(script)],
                           extra_env=dict(WORKER_ENV))
    assert rc == 0
    assert time.monotonic() - t0 < 30.0
    assert driver.rounds == 2
    assert metrics.get_counter("elastic.round_timeout") == 1


@pytest.mark.faults
def test_corrupt_checkpoint_falls_back_in_elastic_context(tmp_path):
    """Corruption injected at checkpoint-write time (seeded plan) is
    detected on restore and resume falls back to the last good step,
    with the failure counters visible in metrics output."""
    import horovod_tpu as hvd

    metrics.reset_counters("checkpoint.")
    hvd.init()
    try:
        path = str(tmp_path / "ckpt")
        for s in (1, 2):
            hvd.save_checkpoint(path, {"epoch": s}, step=s,
                                use_orbax=False)
        faults.set_plan("checkpoint.write:corrupt:nth=1")
        try:
            hvd.save_checkpoint(path, {"epoch": 3}, step=3,
                                use_orbax=False)
        finally:
            faults.set_plan(None)
        state, step = hvd.restore_or_init(path, {"epoch": 0})
        assert (state["epoch"], step) == (2, 2)
        got = metrics.get_counters("checkpoint.")
        assert got["checkpoint.corrupt_detected"] >= 1
        assert got["checkpoint.fallback"] >= 1
    finally:
        hvd.shutdown()


# ---- unified telemetry: the PR-2 acceptance scenario ------------------

TELEMETRY_WORKER_SCRIPT = textwrap.dedent(
    """
    import os, pickle, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    from horovod_tpu import faults, metrics
    from horovod_tpu.runner import elastic_worker
    from horovod_tpu.utils.timeline import Timeline

    round_id = int(os.environ["HVD_TPU_ELASTIC_ROUND"])
    rank = int(os.environ["HVD_TPU_CROSS_RANK"])
    size = int(os.environ["HVD_TPU_CROSS_SIZE"])
    host = os.environ["HVD_TPU_HOSTNAME"]

    mgr = elastic_worker.get_notification_manager()
    mgr.init()  # KV connect + heartbeats (which push metric snapshots)

    tl = Timeline(
        os.environ["TRACE_DIR"] + f"/timeline.rank{rank}.json", rank=rank
    )
    blob = mgr.load_state_blob()
    epoch = pickle.loads(blob) if blob else 0
    target = int(os.environ.get("TARGET_EPOCHS", "6"))
    while epoch < target:
        time.sleep(float(os.environ.get("EPOCH_SECS", "0.4")))
        faults.inject("worker.step", rank=rank, round=round_id,
                      host=host, epoch=epoch)
        epoch += 1
        metrics.inc_counter("train.steps")
        metrics.observe("train.step_seconds", 0.4)
        tl.record_op(f"epoch{epoch}", "STEP", 0)
        mgr.save_state_blob(pickle.dumps(epoch))
    tl.close()
    mgr.close()
    """
)


@pytest.mark.faults
def test_fault_injected_run_produces_postmortem_record(tmp_path):
    """PR-2 acceptance criteria end to end: one fault-injected elastic
    run (PR 1's HVD_TPU_FAULT_PLAN) yields (1) per-rank timelines that
    merge into a valid Chrome trace with rank lanes, (2) a live
    Prometheus scrape from the driver's /metrics endpoint carrying
    hvd_tpu_ counter/gauge/histogram families (driver-local and
    worker-pushed), and (3) a JSONL elastic event log that reconstructs
    the injected failure sequence in order."""
    import json as _json
    import urllib.request

    from horovod_tpu import events

    metrics.reset_counters()
    event_log = str(tmp_path / "elastic_events.jsonl")
    events.set_event_log(events.EventLog(event_log))
    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    script = tmp_path / "worker.py"
    script.write_text(TELEMETRY_WORKER_SCRIPT)

    discovery = ScriptedDiscovery([(1e9, {"localhost": 1, "127.0.0.1": 1})])
    driver = ElasticDriver(
        HostManager(discovery, cooldown_s=1.0, cooldown_max_s=4.0),
        min_np=1, max_np=2, telemetry_port=0,
    )
    driver.start_discovery()
    scrapes = []

    def run():
        rc = driver.run_rounds(
            [sys.executable, str(script)],
            extra_env={
                "TRACE_DIR": str(trace_dir),
                "TARGET_EPOCHS": "6",
                "EPOCH_SECS": "0.4",
                "HVD_TPU_ELASTIC_EVENT_LOG": event_log,
                "HVD_TPU_FAULT_PLAN":
                    "worker.step:crash:host=127.0.0.1,round=1,nth=1,code=9",
                **WORKER_ENV,
            },
        )
        scrapes.append(("rc", rc))

    t = threading.Thread(target=run)
    t.start()
    try:
        deadline = time.monotonic() + 60.0
        got_worker_series = False
        while t.is_alive() and time.monotonic() < deadline:
            srv = driver._telemetry
            if srv is not None:
                try:
                    body = urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}/metrics", timeout=2
                    ).read().decode()
                    scrapes.append(("metrics", body))
                    if 'rank="' in body:
                        got_worker_series = True
                    health = _json.loads(urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}/health", timeout=2
                    ).read())
                    scrapes.append(("health", health))
                except Exception:
                    pass  # endpoint races the round teardown
            time.sleep(0.5)
    finally:
        t.join(timeout=60)
        events.set_event_log(None)
    assert not t.is_alive(), "elastic run did not finish"
    assert ("rc", 0) in scrapes

    # (2) Prometheus scrape: hvd_tpu_ families of all three kinds, from
    # the driver registry and from worker pushes (rank-labeled).
    bodies = [b for k, b in scrapes if k == "metrics"]
    assert bodies, "never scraped /metrics"
    final = bodies[-1]
    assert "hvd_tpu_elastic_rounds_total" in final          # counter
    assert "hvd_tpu_elastic_round " in final or \
        "hvd_tpu_elastic_round{" in final                    # gauge
    assert got_worker_series, "no worker-pushed rank series ever seen"
    joined = "\n".join(bodies)
    assert "hvd_tpu_train_steps_total{rank=" in joined
    assert "hvd_tpu_train_step_seconds_bucket" in joined     # histogram
    healths = [h for k, h in scrapes if k == "health"]
    assert healths and all("round" in h for h in healths)

    # (3) the event log reconstructs the injected failure sequence
    evs = events.read_events(event_log)
    names = [e["event"] for e in evs]
    assert "round_start" in names and "worker_crash" in names
    assert "blacklist" in names and "round_end" in names
    i_start = names.index("round_start")
    i_crash = names.index("worker_crash")
    i_black = names.index("blacklist")
    assert i_start < i_crash < i_black, names
    crash = evs[i_crash]
    assert crash["host"] == "127.0.0.1" and crash["verdict"] == "crash"
    assert crash["round"] == 1
    # both clocks present; driver-side order is monotonic
    driver_evs = [e for e in evs if e["pid"] == os.getpid()]
    monos = [e["mono_ts"] for e in driver_evs]
    assert monos == sorted(monos)
    # the run recovered: a later round started after the blacklist
    later_rounds = [e for e in evs[i_black:] if e["event"] == "round_start"]
    assert later_rounds and later_rounds[-1]["round"] >= 2

    # (1) per-rank timelines merge into one valid Chrome trace
    traces = sorted(
        str(trace_dir / f) for f in os.listdir(trace_dir)
        if f.endswith(".json")
    )
    assert len(traces) >= 2, traces
    merged = hvd_merge(traces)
    _json.loads(_json.dumps(merged))  # valid JSON (Perfetto-loadable)
    lanes = {e["pid"] for e in merged["traceEvents"]}
    assert lanes == {0, 1}, lanes
    steps = [e for e in merged["traceEvents"] if e.get("cat") == "STEP"]
    assert steps, "no per-epoch step events in the merged trace"


def hvd_merge(paths):
    from horovod_tpu.utils.timeline import merge_timeline_files

    return merge_timeline_files(paths)
