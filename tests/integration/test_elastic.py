"""Elastic integration: real worker processes, membership change
mid-training, state carried across rounds.

Reference analog: ``test/integration/elastic_common.py`` +
``test_elastic_torch.py`` — scripted discovery emitting different host
lists over time, real elastic jobs, asserting world sizes and state
continuity per round.
"""

import os
import sys
import textwrap
import threading
import time

import pytest

from horovod_tpu.elastic.discovery import HostDiscovery, HostManager
from horovod_tpu.runner.elastic_driver import ElasticDriver

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
WORKER_ENV = {
    "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
}

pytestmark = pytest.mark.integration

WORKER_SCRIPT = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    from horovod_tpu.elastic import ObjectState

    hvd.init()
    out = open(os.environ["RESULTS_FILE"] + f".{os.environ['HVD_TPU_CROSS_RANK']}", "a")

    state = ObjectState(epoch=0)

    @hvd.elastic.run
    def train(state):
        while state.epoch < 6:
            time.sleep(0.8)  # one "epoch" of work
            state.epoch += 1
            print(f"epoch {state.epoch} world {hvd.size()}", flush=True)
            out.write(f"round={os.environ['HVD_TPU_ELASTIC_ROUND']} "
                      f"epoch={state.epoch} size={hvd.size()}\\n")
            out.flush()
            state.commit()
        return state.epoch

    import time
    final = train(state)
    out.write(f"done epoch={final}\\n")
    out.close()
    """
)


class ScriptedDiscovery(HostDiscovery):
    """Host set changes after a delay (the scripted-discovery fake)."""

    def __init__(self, phases):
        # phases: list of (duration_s, {host: slots}); last phase persists
        self._phases = phases
        self._t0 = time.monotonic()

    def find_available_hosts_and_slots(self):
        t = time.monotonic() - self._t0
        acc = 0.0
        for duration, hosts in self._phases:
            acc += duration
            if t < acc:
                return dict(hosts)
        return dict(self._phases[-1][1])


def test_elastic_membership_change(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)
    results_file = str(tmp_path / "results")

    discovery = ScriptedDiscovery([
        (3.0, {"localhost": 2}),
        (1e9, {"localhost": 3}),  # scale up after 3s
    ])
    driver = ElasticDriver(HostManager(discovery), min_np=2, max_np=4)
    driver.start_discovery()
    rc = driver.run_rounds(
        [sys.executable, str(script)],
        extra_env={"RESULTS_FILE": results_file, **WORKER_ENV},
    )
    assert rc == 0
    assert driver.rounds >= 2, "membership change should have forced a new round"

    # parse per-rank logs: epochs must be monotonic across rounds (state
    # survived the restart) and the final round must run at size 3
    lines = []
    for fn in os.listdir(tmp_path):
        if fn.startswith("results."):
            lines += (tmp_path / fn).read_text().splitlines()
    assert any(l.startswith("done epoch=6") for l in lines)
    by_round = {}
    for l in lines:
        if l.startswith("round="):
            parts = dict(kv.split("=") for kv in l.split())
            by_round.setdefault(int(parts["round"]), []).append(
                (int(parts["epoch"]), int(parts["size"]))
            )
    first_round = min(by_round)
    last_round = max(by_round)
    assert first_round != last_round
    assert all(s == 2 for _, s in by_round[first_round])
    assert all(s == 3 for _, s in by_round[last_round])
    max_epoch_first = max(e for e, _ in by_round[first_round])
    min_epoch_last = min(e for e, _ in by_round[last_round])
    assert min_epoch_last >= max_epoch_first, (
        f"state lost across rounds: round {first_round} reached "
        f"{max_epoch_first}, round {last_round} restarted at {min_epoch_last}"
    )


COST_WORKER_SCRIPT = textwrap.dedent(
    """
    import os, sys, time
    t_start = time.perf_counter()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax
    import horovod_tpu as hvd
    from horovod_tpu.elastic import ObjectState

    hvd.init()
    params = {"w": jnp.zeros((32, 32)), "b": jnp.zeros((32,))}
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))

    def loss_fn(p, batch):
        h = jnp.tanh(batch @ p["w"] + p["b"])
        return jnp.mean((h @ p["w"]) ** 2)

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)
    batch = jnp.ones((4 * hvd.size(), 32))
    state = ObjectState(epoch=0)

    first_step = [True]

    @hvd.elastic.run
    def train(state):
        global params, opt_state
        while state.epoch < 4:
            p2, o2, loss = step(params, opt_state, batch)
            params, opt_state = p2, o2
            float(loss)
            if first_step[0]:
                first_step[0] = False
                # init -> first completed step = the round's restart cost
                # (fresh process per round, so this fires once per round)
                cost = time.perf_counter() - t_start
                with open(os.environ["RESULTS_FILE"]
                          + f".{os.environ['HVD_TPU_CROSS_RANK']}", "a") as fh:
                    fh.write(f"round={os.environ['HVD_TPU_ELASTIC_ROUND']} "
                             f"restart_cost_s={cost:.3f}\\n")
            time.sleep(0.4)
            state.epoch += 1
            state.commit()
        return state.epoch

    train(state)
    """
)


def test_elastic_restart_cost_bounded(tmp_path):
    """Measures the full cost of a membership-change restart (process
    respawn + hvd re-init + recompile + first step) and bounds the
    second round via the persistent XLA compilation cache (reference
    concern: elastic reset cost; TPU twist: recompilation dominates, so
    JAX_COMPILATION_CACHE_DIR turns round-2 compiles into cache reads)."""
    script = tmp_path / "worker.py"
    script.write_text(COST_WORKER_SCRIPT)
    results_file = str(tmp_path / "results")
    cache_dir = str(tmp_path / "xla_cache")

    discovery = ScriptedDiscovery([
        (2.5, {"localhost": 2}),
        (1e9, {"localhost": 3}),
    ])
    driver = ElasticDriver(HostManager(discovery), min_np=2, max_np=4)
    driver.start_discovery()
    rc = driver.run_rounds(
        [sys.executable, str(script)],
        extra_env={
            "RESULTS_FILE": results_file,
            "JAX_COMPILATION_CACHE_DIR": cache_dir,
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
            "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
            **WORKER_ENV,
        },
    )
    assert rc == 0
    assert driver.rounds >= 2

    costs = {}
    for fn in os.listdir(tmp_path):
        if fn.startswith("results."):
            for l in (tmp_path / fn).read_text().splitlines():
                parts = dict(kv.split("=") for kv in l.split())
                rnd = int(parts["round"])
                costs.setdefault(rnd, []).append(
                    float(parts["restart_cost_s"])
                )
    assert len(costs) >= 2, f"need costs from >=2 rounds, got {costs}"
    first, last = min(costs), max(costs)
    c1 = max(costs[first])
    c2 = max(costs[last])
    print(f"elastic restart cost: round{first}={c1:.2f}s "
          f"round{last}={c2:.2f}s (cache dir {cache_dir})")
    # The restart (world resize!) must not cost more than the cold
    # start plus slack: compile work is bounded by the persistent
    # cache.  The slack is generous because this is wall-clock on a
    # shared box — under a fully loaded single-core host (e.g. the
    # whole matrix running) scheduler noise alone can double a round.
    assert c2 <= c1 * 3.0 + 5.0, (first, c1, last, c2)


def test_elastic_worker_failure_blacklists_and_continues(tmp_path):
    """A worker that dies is handled: the driver starts a new round
    (reference fault-tolerance-without-scaling case)."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(
        """
        import os, sys, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
        import horovod_tpu as hvd
        hvd.init()
        round_id = int(os.environ["HVD_TPU_ELASTIC_ROUND"])
        rank = int(os.environ["HVD_TPU_CROSS_RANK"])
        host = os.environ["HVD_TPU_HOSTNAME"]
        marker = os.environ["RESULTS_FILE"] + f".round{round_id}.rank{rank}"
        open(marker, "w").write(f"size={hvd.size()} host={host}\\n")
        if round_id == 1 and host == "127.0.0.1":
            os._exit(7)  # simulated crash of the 127.0.0.1 "host"
        time.sleep(1.0)
        """
    ))
    results_file = str(tmp_path / "marks")
    discovery = ScriptedDiscovery([(1e9, {"localhost": 1, "127.0.0.1": 1})])
    driver = ElasticDriver(HostManager(discovery), min_np=1, max_np=2)
    driver.start_discovery()
    rc = driver.run_rounds(
        [sys.executable, str(script)],
        extra_env={"RESULTS_FILE": results_file, **WORKER_ENV},
    )
    assert rc == 0
    assert driver.rounds == 2
    marks = sorted(os.listdir(tmp_path))
    assert any("round2" in m for m in marks)
